"""SGD matrix factorization — the model-rotation flagship (Model B).

Reference parity: Harp's SGD-MF (ml/java sgd/SGDCollectiveMapper.java:54 and the
DAAL-2019 variant experimental/daal_sgd/SGDDaalCollectiveMapper.java:75 — BASELINE's
"harp-daal SGD-MF"). The reference design: rating rows are data-local, the item
factor matrix H is split into ``numModelSlices`` tables that ring-rotate among
workers (Rotator, dymoro/Rotator.java:30); within each rotation hop a timer-bounded
``Scheduler`` (dymoro/Scheduler.java:85-160) randomly schedules (row-split,
col-slice) blocks onto threads running asynchronous SGD point updates.

TPU-native re-expression:

* **Rotation** is a ``ppermute`` ring schedule (`collectives.rotation.Rotator`);
  after B hops every H block has visited every worker and is home again. The whole
  multi-epoch loop is ONE compiled XLA program.
* **The timer-bounded async scheduler** is host-driven and data-dependent — hostile
  to XLA (SURVEY §7 "hard parts"). Reformulated as **bounded staleness**: each hop
  runs a fixed number of mini-batch SGD steps over that (worker, block) bucket of
  ratings. Convergence-equivalent, not step-equivalent; Harp itself only claims
  statistical semantics for its racy Hogwild-style updates. The per-hop budget can
  be auto-tuned between epochs by :class:`HopBudgetTuner` /
  :meth:`SGDMF.fit_adaptive` — the analog of the reference's
  ``adjustMiniBatch``/``setTimer`` (SGDCollectiveMapper.java:281-287, :623):
  buckets are padded to a multiple of ``minibatches_per_hop``, so every divisor
  is a valid budget over the SAME device-resident data (a "banded" shape family
  — switching budgets swaps compiled programs, never re-lays-out or re-uploads).

Two data layouts, selected by density (``SGDMFConfig.layout``):

* **dense** (masked dense-stripe): when the per-worker rating slab fits HBM, store
  the (rows × cols) block as ONE dense bf16 matrix whose missing entries are
  NaN-encoded (no separate mask slab) and express each minibatch as three
  GEMMs — ``pred = W_s @ H_b^T``, ``dW = G @ H_b``, ``dH = G^T @ W_s`` with
  ``G = where(isnan(V), 0, V - pred)``. This burns redundant FLOPs on missing
  entries but runs entirely on the MXU with **zero gathers/scatters**, which
  on TPU is ~50× faster than an index-chasing loop at MovieLens/Netflix-like
  densities (the per-row gather granularity, not HBM bandwidth, is the sparse
  ceiling). Same update rule as the sparse path — same minibatch gradient
  formula, same L2 term (missing entries contribute exactly zero to G, and
  the regularizer is scaled by true per-row/per-col counts, precomputed
  host-side) — but the slab stores ratings in bf16 (~8-bit mantissa), so
  values/residuals are quantized: the two layouts are convergence-equivalent,
  not bit-identical. Input NaN values are rejected at validation — NaN is the
  missing-entry sentinel.
* **sparse** (padded COO buckets): for data too sparse/large to densify. Ratings
  are pre-sorted on the host into a (W workers × B column-blocks) grid of padded
  COO buckets; the inner loop is gather → rank-K dot → two scatter-adds. Hot
  rows/columns are spread by **balanced (serpentine-LPT) id assignment** so one
  power-law row or column cannot blow up the shared bucket padding (the
  reference's marquee datasets — clueweb — are exactly Zipf-distributed; its
  regroup of VSets achieved the same load-spreading by hash partitioning,
  HarpDAALDataSource.regroupCOOList:399).

Duplicate (row, col) pairs are dropped (keep-first) in ``prepare`` for BOTH
layouts so the two paths always train on the identical entry set; the count is
reported in ``last_layout_stats["duplicates_dropped"]``.

RMSE per epoch is accumulated on the fly (pre-update residuals) and combined with an
allreduce — the reference's test-RMSE allreduce (SGDCollectiveMapper.java:615-641).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu import telemetry
from harp_tpu.collectives import lax_ops, quantize, rotation
from harp_tpu.ops import ring_dma
from harp_tpu.ops import lane_pack, pallas_kernels
from harp_tpu.parallel.mesh import fetch
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class SGDMFConfig:
    """Mirrors the reference CLI (r, lambda, epsilon/lr, numIterations,
    numModelSlices → here the slice count is the worker count by construction)."""

    rank: int = 16
    lam: float = 0.05          # L2 regularization (reference: lambda)
    lr: float = 0.05           # learning rate (reference: epsilon)
    epochs: int = 10
    minibatches_per_hop: int = 4  # bounded-staleness stand-in for the dymoro timer
    num_slices: int = 1        # 2 = double-buffered pipeline (reference:
    #                            numModelSlices=2, dymoro comm/compute overlap)
    layout: str = "auto"       # auto | dense | sparse
    quant: Optional[str] = None  # None | "int8" | "bf16": quantize the H-block
    #                              rotation hops' WIRE format with error
    #                              feedback carried in the rotation scan
    #                              (collectives/quantize.py). Dequantize-
    #                              after-transport: updates run f32; the
    #                              trajectory is convergence-equivalent to
    #                              f32, not bit-identical (tests pin a
    #                              per-codec RMSE tolerance).
    dense_max_bytes: int = 6_000_000_000  # per-worker slab budget for auto-dense
    balance: bool = True       # serpentine-LPT id balancing for the sparse layout
    reshard: str = "auto"      # r12: HOW a world-size-changing resume moves
    #   the factor tables onto this session's layout (arXiv:2112.01075):
    #   "device" = collective redistribution on the mesh (collectives/
    #   reshard.py alltoall schedule — bitwise, chunk-bounded rounds, no
    #   host gather of a sharded leaf), "ring" = the ppermute schedule
    #   (rides lax_ops.rotate, so DCN link-class chunking composes),
    #   "host" = the PR 8 numpy gather-and-resplit (kept as the parity
    #   oracle and small-world fallback), "auto" = device when the mesh has
    #   >1 worker, host on a 1-worker mesh (nothing to redistribute).
    reshard_chunk_bytes: int = 0   # 0 = collectives.reshard default (1 MiB)
    fused_dma: bool = False    # r10: H-block rotation hops ride the fused
    #   ring-DMA engine (ops/ring_dma) instead of ppermute. On TPU with the
    #   fused dense hop kernel live, the hop fuses INTO the kernel
    #   (dense_mf_hop_pallas ring_hop: H leaves VMEM straight for the
    #   neighbor's HBM — the ppermute staging round trips vanish); every
    #   other path hops through ring_dma.hop. Bitwise-identical to the
    #   ppermute schedule on every backend (the engine moves bytes, it
    #   never rounds); off-TPU the tagged fallback keeps the jaxpr budget's
    #   fused_dma rows honest. A quantized wire (quant=) takes precedence
    #   over fusion (rotation.py module doc).


# --------------------------------------------------------------------------- #
# Host-side layout planning
# --------------------------------------------------------------------------- #

def serpentine_assign(counts: np.ndarray, num_bins: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Balanced id→bin assignment: sort ids by descending weight, deal them out
    in serpentine (boustrophedon) order. Each bin receives exactly
    ``ceil(n/num_bins)`` or ``floor`` ids, and loads are near-LPT balanced.

    Returns ``(bin_of_id, local_slot_of_id)``. This is the skew-defense for the
    sparse layout: a Zipf head row/column lands alone in a lightly-loaded bin
    instead of inflating the global bucket padding.
    """
    n = len(counts)
    order = np.argsort(-np.asarray(counts), kind="stable")
    ranks = np.empty(n, np.int64)
    ranks[order] = np.arange(n)
    chunk, pos = np.divmod(ranks, num_bins)
    bins = np.where(chunk % 2 == 0, pos, num_bins - 1 - pos)
    return bins.astype(np.int32), chunk.astype(np.int32)


def identity_assign(n: int, num_bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Contiguous-range assignment (the round-1 behavior)."""
    per = -(-n // num_bins)
    ids = np.arange(n)
    return (ids // per).astype(np.int32), (ids % per).astype(np.int32)


def dedupe_coo(rows, cols, vals, num_cols):
    """Keep-FIRST dedupe of (row, col) pairs — the shared contract for every
    dual-layout model (SGD-MF and ALS): sparse and dense paths must train on
    the identical entry set, so duplicates are resolved once, here, before
    layout dispatch. Returns (rows, cols, vals, dropped_count)."""
    if not len(rows):
        return rows, cols, vals, 0
    keys = rows.astype(np.int64) * num_cols + cols
    _, first = np.unique(keys, return_index=True)
    if len(first) == len(rows):
        return rows, cols, vals, 0
    dropped = len(rows) - len(first)
    first.sort()
    return rows[first], cols[first], vals[first], dropped


def _validate_coo(rows, cols, num_rows, num_cols, vals=None):
    if vals is not None and len(vals) and np.isnan(vals).any():
        raise ValueError("rating values must not be NaN (NaN encodes missing "
                         "entries in the dense layout)")
    if len(rows):
        if rows.min() < 0 or rows.max() >= num_rows:
            raise ValueError(
                f"row indices must be in [0, {num_rows}); got "
                f"[{rows.min()}, {rows.max()}]")
        if cols.min() < 0 or cols.max() >= num_cols:
            raise ValueError(
                f"col indices must be in [0, {num_cols}); got "
                f"[{cols.min()}, {cols.max()}]")


def bucketize(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_workers: int,
    num_rows: int,
    num_cols: int,
    minibatches: int,
    num_col_blocks: int = 0,
    row_assign: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    col_assign: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    validate: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int, int]:
    """Host-side layout: COO ratings → (W, B, M) padded buckets.

    Bucket (w, b) holds the ratings whose row lives on worker w and whose column
    lives in H block b, with row/col indices localized to the block. This replaces
    the reference's regroup of VSets (SGDCollectiveMapper regroup-vw:384): the
    shuffle happens once on the host, the device program is static.
    ``num_col_blocks`` defaults to W (one H block per worker); the 2-slice
    pipeline uses 2W. ``row_assign``/``col_assign`` are optional (bin, slot)
    id maps (see :func:`serpentine_assign`); default is contiguous ranges.
    """
    if validate:
        _validate_coo(rows, cols, num_rows, num_cols)
    w = num_workers
    b_blocks = num_col_blocks or w
    rpw = -(-num_rows // w)        # rows per worker (ceil)
    cpb = -(-num_cols // b_blocks)  # cols per block
    if row_assign is None:
        row_assign = identity_assign(num_rows, w)
    if col_assign is None:
        col_assign = identity_assign(num_cols, b_blocks)
    owner, r_slot = row_assign[0][rows], row_assign[1][rows]
    block, c_slot = col_assign[0][cols], col_assign[1][cols]
    # One sort-based pass: order entries by (owner, block), then lay each bucket
    # out contiguously — O(nnz log nnz), not O(W^2 * nnz).
    bucket = owner.astype(np.int64) * b_blocks + block
    order = np.argsort(bucket, kind="stable")
    counts = np.bincount(bucket, minlength=w * b_blocks)
    m = max(int(counts.max()), 1) if counts.size else 1
    m = -(-m // minibatches) * minibatches   # pad so hops split evenly
    r_idx = np.zeros((w, b_blocks, m), np.int32)
    c_idx = np.zeros((w, b_blocks, m), np.int32)
    val = np.zeros((w, b_blocks, m), np.float32)
    mask = np.zeros((w, b_blocks, m), np.float32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    rs, cs, vs = r_slot[order], c_slot[order], vals[order]
    for b in range(w * b_blocks):
        lo, hi = starts[b], starts[b + 1]
        if lo == hi:
            continue
        wi, bi = divmod(b, b_blocks)
        k = hi - lo
        r_idx[wi, bi, :k] = rs[lo:hi]
        c_idx[wi, bi, :k] = cs[lo:hi]
        val[wi, bi, :k] = vs[lo:hi]
        mask[wi, bi, :k] = 1.0
    return r_idx, c_idx, val, mask, rpw, cpb


# --------------------------------------------------------------------------- #
# Model
# --------------------------------------------------------------------------- #

class SGDMF:
    """Distributed SGD matrix factorization over a HarpSession mesh."""

    def __init__(self, session: HarpSession, config: SGDMFConfig):
        self.session = session
        self.config = config
        self._compiled = {}       # layout/shape key -> compiled SPMD program
        self._warm: dict = {}     # key -> AOT-compiled executable (fit_adaptive)
        self.last_layout_stats: dict = {}

    # -- schedule (shared by both layouts) ----------------------------------- #

    def _bucket_id(self, wid, t, w):
        """Which (globally-numbered) column block is resident at hop t.

        1-slice: plain ring — block (wid - t) mod W. 2-slice: the dymoro
        pipeline (Rotator, numModelSlices=2): resident slice s = t%2 has been
        shifted t//2 times; compute on it while the other slice's ppermute is
        in flight."""
        if self.config.num_slices == 2:
            s = t % 2
            return s * w + (wid - t // 2) % w
        return (wid - t) % w

    def _build(self, w: int, num_data_args: int,
               make_update_bucket: Callable, epochs: int,
               body_hops: bool = False):
        """Shared rotation/epoch harness for both layouts.

        ``make_update_bucket(local_data)`` receives the worker-local shards of
        the data arrays (leading worker axis stripped) and returns
        ``update_bucket(w_local, h_block, sse, cnt, bucket_id)`` — the only
        part that differs between the sparse and dense programs.

        ``body_hops``: the update itself performs the ring hop (the fused
        dense kernel's in-kernel remote-copy epilogue returns the NEXT
        resident block), so the rotation scan runs shift=0 — the schedule
        is unchanged, only the transport moved into the kernel.
        """
        cfg = self.config
        two_slice = cfg.num_slices == 2

        def fit_fn(*args):
            data, (w0, h0) = args[:num_data_args], args[num_data_args:]
            update_bucket = make_update_bucket(tuple(d[0] for d in data))

            def hop_body(carry, h_block, t):
                w_local, sse, cnt = carry
                wid = lax_ops.worker_id()
                bucket_id = self._bucket_id(wid, t, w)
                w_local, h_block, sse, cnt = update_bucket(
                    w_local, h_block, sse, cnt, bucket_id)
                return (w_local, sse, cnt), h_block

            rotator = rotation.Rotator(
                w, cfg.num_slices,
                comm=(quantize.CommConfig(quant=cfg.quant)
                      if cfg.quant is not None else None),
                fused_dma=cfg.fused_dma and not body_hops,
                shift=0 if body_hops else 1)

            def epoch(state, _):
                w_local, h = state
                carry0 = (w_local, jnp.zeros(()), jnp.zeros(()))
                slices = h if two_slice else (h,)
                (w_local, sse, cnt), out = rotator.run(hop_body, carry0,
                                                       slices)
                h = out if two_slice else out[0]
                sse = jax.lax.psum(sse, lax_ops.WORKERS)
                cnt = jax.lax.psum(cnt, lax_ops.WORKERS)
                return (w_local, h), jnp.sqrt(sse / jnp.maximum(cnt, 1.0))

            # two-slice h0 arrives as this worker's (1, 2, cpb, K) chunk:
            # slice A block w and slice B block W+w
            h_init = (h0[0, 0], h0[0, 1]) if two_slice else h0
            (w_local, h_fin), rmse = jax.lax.scan(
                epoch, (w0, h_init), None, length=epochs)
            if two_slice:
                h_fin = jnp.stack(h_fin, axis=0)[None]   # (1, 2, cpb, K)
            return w_local, h_fin, rmse

        sess = self.session
        return sess.spmd(
            fit_fn,
            in_specs=(sess.shard(),) * (num_data_args + 2),
            out_specs=(sess.shard(), sess.shard(), sess.replicate()),
        )

    # -- sparse (padded COO bucket) program ----------------------------------- #

    def _build_sparse(self, w: int, nmb: int, mbs: int, epochs: int):
        lr, lam = self.config.lr, self.config.lam

        def make_update_bucket(data):
            r_idx, c_idx, val, mask = data

            def update_bucket(w_local, h_block, sse, cnt, bucket_id):
                """Run the minibatched SGD updates of one (worker, block)
                bucket against the resident H block."""
                r = jnp.take(r_idx, bucket_id, axis=0).reshape(nmb, mbs)
                c = jnp.take(c_idx, bucket_id, axis=0).reshape(nmb, mbs)
                v = jnp.take(val, bucket_id, axis=0).reshape(nmb, mbs)
                msk = jnp.take(mask, bucket_id, axis=0).reshape(nmb, mbs)

                def mb_step(state, xs):
                    wl, hb, sse, cnt = state
                    rm, cm, vm, mm = xs
                    wr = wl[rm]                      # (mbs, K)
                    hc = hb[cm]
                    pred = jnp.sum(wr * hc, axis=-1)
                    err = (vm - pred) * mm
                    wl = wl.at[rm].add(
                        lr * (err[:, None] * hc - lam * wr * mm[:, None]))
                    hb = hb.at[cm].add(
                        lr * (err[:, None] * wr - lam * hc * mm[:, None]))
                    return (wl, hb, sse + jnp.sum(err * err),
                            cnt + jnp.sum(mm)), None

                (w_local, h_block, sse, cnt), _ = jax.lax.scan(
                    mb_step, (w_local, h_block, sse, cnt), (r, c, v, msk))
                return w_local, h_block, sse, cnt

            return update_bucket

        return self._build(w, 4, make_update_bucket, epochs)

    # -- dense (masked stripe-GEMM) program ------------------------------------ #

    def _build_dense(self, w: int, nmb: int, nmb_fine: int, rpw: int,
                     cpb: int, epochs: int):
        lr, lam = self.config.lr, self.config.lam
        s_rows = rpw // nmb
        bf = jnp.bfloat16
        # dense-stripe tiling rides the shared lane engine's constant:
        # a fused-hop column tile must be a whole number of 128-lane
        # MXU tiles AND divide the column block
        col_tile = next((ct for ct in (4 * lane_pack.LANES,
                                       2 * lane_pack.LANES,
                                       lane_pack.LANES)
                         if cpb % ct == 0), 0)
        fused = col_tile and pallas_kernels.use_dense_mf_pallas(
            cpb, s_rows, self.config.rank)
        # in-kernel ring hop (r10): fused dense kernel + fused_dma + a plain
        # (unquantized) multi-worker wire (quant takes the encode path) on
        # the 1-slice schedule ONLY — the kernel's blocking send+wait would
        # defeat the 2-slice pipeline's compute/DMA overlap, so 2-slice
        # keeps the out-of-kernel fused hop. The kernel then returns the
        # already-hopped H block, so _build runs the rotation scan with
        # shift=0 (body_hops).
        ring_hop = bool(fused and self.config.fused_dma and w > 1
                        and self.config.num_slices == 1
                        and self.config.quant is None
                        and ring_dma.use_ring_dma())

        def make_update_bucket(data):
            # missing entries are NaN-encoded in the value slab — no separate
            # mask slab (halves slab memory and cuts a quarter of the epoch's
            # HBM traffic; measured +14% samples/s, identical SSE)
            v_slab, row_cnt, col_cnt = data

            def _run_stripes_pallas(w_local, h_block, sse, cnt, vb, rcnt,
                                    ccnt, col_tile, ring_hop):
                # fused hop kernel: pred/G stay in VMEM → one slab read per
                # hop instead of XLA's ~5 slab-sized passes (pallas_kernels
                # module doc). Factors ride transposed (K, rows). With
                # ring_hop the kernel ALSO ships the updated H to the ring
                # neighbor (VMEM → remote HBM, ops/ring_dma) and the
                # returned block is the received one — the rotation scan
                # then runs shift=0 (body_hops).
                if ring_hop:
                    w_t, _h_t, hop_sse, h_next = (
                        pallas_kernels.dense_mf_hop_pallas(
                            vb, w_local.T, h_block.T,
                            rcnt.reshape(nmb, s_rows), ccnt, lr, lam,
                            col_tile=col_tile, ring_hop=True))
                    return (w_t.T, h_next.T, sse + hop_sse,
                            cnt + jnp.sum(ccnt))
                w_t, h_t, hop_sse = pallas_kernels.dense_mf_hop_pallas(
                    vb, w_local.T, h_block.T, rcnt.reshape(nmb, s_rows),
                    ccnt, lr, lam, col_tile=col_tile)
                return (w_t.T, h_t.T, sse + hop_sse,
                        cnt + jnp.sum(ccnt))

            def _run_stripes(w_local, h_block, sse, cnt, vb, rcnt, ccnt):
                def stripe(state, xs):
                    hb, sse = state
                    w_s, v_s, rc_s, cc_s = xs
                    # pred/G/dW/dH are three MXU GEMMs; bf16 inputs, f32
                    # accumulation (matches the fused pallas hop bit-for-bit)
                    hb_b = hb.astype(bf)
                    pred = jax.lax.dot_general(
                        w_s.astype(bf), hb_b, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)  # (s, cpb)
                    g = jnp.where(jnp.isnan(v_s), jnp.asarray(0.0),
                                  v_s.astype(jnp.float32) - pred
                                  ).astype(bf)               # bf16, masked
                    dw = jax.lax.dot_general(
                        g, hb_b, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)  # (s, K)
                    dh = jax.lax.dot_general(
                        g, w_s.astype(bf), (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)  # (cpb, K)
                    w_s = w_s + lr * (dw - lam * rc_s[:, None] * w_s)
                    hb = hb + lr * (dh - lam * cc_s[:, None] * hb)
                    sse = sse + jnp.sum(g.astype(jnp.float32) ** 2)
                    return (hb, sse), w_s

                (h_block, sse), w_new = jax.lax.scan(
                    stripe,
                    (h_block, sse),
                    (w_local.reshape(nmb, s_rows, -1),
                     vb.reshape(nmb, s_rows, cpb),
                     rcnt.reshape(nmb, s_rows),
                     ccnt))
                cnt = cnt + jnp.sum(ccnt)
                return w_new.reshape(rpw, -1), h_block, sse, cnt

            def update_bucket(w_local, h_block, sse, cnt, bucket_id):
                if v_slab.shape[0] == 1:
                    # single-block mesh (W=1, 1 slice): static index — the
                    # dynamic-slice would copy the full slab (GBs) every hop
                    vb, rcnt, ccnt = v_slab[0], row_cnt[0], col_cnt[0]
                else:
                    vb = jnp.take(v_slab, bucket_id, axis=0)   # (rpw, cpb)
                    rcnt = jnp.take(row_cnt, bucket_id, axis=0)
                    ccnt = jnp.take(col_cnt, bucket_id, axis=0)
                # col counts are stored at the finest stripe granularity
                # (nmb_fine, cpb); coarser budgets sum adjacent fine stripes
                ccnt = ccnt.reshape(nmb, nmb_fine // nmb, cpb).sum(axis=1)
                if fused:
                    return _run_stripes_pallas(w_local, h_block, sse, cnt,
                                               vb, rcnt, ccnt, col_tile,
                                               ring_hop)
                return _run_stripes(w_local, h_block, sse, cnt, vb, rcnt,
                                    ccnt)

            return update_bucket

        return self._build(w, 3, make_update_bucket, epochs,
                           body_hops=ring_hop)

    def _program(self, layout: str, nmb: int, epochs: int, geom: Tuple):
        """Compile (or fetch) the SPMD program for a given per-hop budget.

        ``geom`` is the layout geometry captured at prepare time — buckets are
        padded to a multiple of ``minibatches_per_hop``, so every divisor
        ``nmb`` yields a valid program over the same device arrays."""
        w = self.session.num_workers
        if layout == "sparse":
            (m_total,) = geom
            if m_total % nmb:
                raise ValueError(f"budget {nmb} does not divide bucket {m_total}")
            key = ("sparse", w, nmb, m_total // nmb, self.config.num_slices,
                   epochs)
            if key not in self._compiled:
                self._compiled[key] = self._build_sparse(
                    w, nmb, m_total // nmb, epochs)
        else:
            nmb_fine, rpw, cpb = geom
            if nmb_fine % nmb:
                raise ValueError(f"budget {nmb} does not divide band {nmb_fine}")
            key = ("dense", w, nmb, nmb_fine, rpw, cpb,
                   self.config.num_slices, epochs)
            if key not in self._compiled:
                self._compiled[key] = self._build_dense(
                    w, nmb, nmb_fine, rpw, cpb, epochs)
        return key

    # -- preparation ----------------------------------------------------------- #

    def _dense_geometry(self, num_rows: int, num_cols: int
                        ) -> Tuple[int, int, int]:
        w = self.session.num_workers
        n_blocks = self.config.num_slices * w
        nmb = self.config.minibatches_per_hop
        rpw = -(-num_rows // w)
        rpw = -(-rpw // nmb) * nmb          # stripes must split evenly
        cpb = -(-num_cols // n_blocks)
        return rpw, cpb, n_blocks

    def _choose_layout(self, num_rows: int, num_cols: int) -> str:
        cfg = self.config
        if cfg.layout in ("dense", "sparse"):
            return cfg.layout
        rpw, cpb, n_blocks = self._dense_geometry(num_rows, num_cols)
        slab_elems = rpw * cpb * n_blocks
        # budget densify's PEAK: the NaN-encoded bf16 value slab plus the
        # transient bf16 mask slab alive at the same time (4 B/elem total);
        # and the int32 scatter-index limit must hold for auto to pick dense
        slab_bytes = 4 * slab_elems
        return ("dense" if slab_bytes <= cfg.dense_max_bytes
                and slab_elems < 2 ** 31 else "sparse")

    def prepare(self, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                num_rows: int, num_cols: int, seed: int = 0):
        """Bucketize + place data and init factors on the mesh ONCE.

        Returns an opaque state tuple for :meth:`fit_prepared` — keeps host
        prep and H2D transfer out of timed regions (KMeans.prepare idiom)."""
        cfg = self.config
        if cfg.num_slices not in (1, 2):
            raise ValueError("num_slices must be 1 or 2")
        if cfg.layout not in ("auto", "dense", "sparse"):
            raise ValueError(f"layout must be auto|dense|sparse, got "
                             f"{cfg.layout!r}")
        _validate_coo(rows, cols, num_rows, num_cols, vals)
        # keep-first dedupe for BOTH layouts: identical training sets
        rows, cols, vals, dropped = dedupe_coo(rows, cols, vals, num_cols)
        layout = self._choose_layout(num_rows, num_cols)
        if layout == "dense":
            state = self._prepare_dense(rows, cols, vals, num_rows, num_cols,
                                        seed)
        else:
            state = self._prepare_sparse(rows, cols, vals, num_rows, num_cols,
                                         seed)
        self.last_layout_stats["duplicates_dropped"] = dropped
        return state

    def _init_factors(self, rng, w_rows: int, h_rows: int):
        scale = 1.0 / np.sqrt(self.config.rank)
        w0 = (scale * rng.standard_normal(
            (w_rows, self.config.rank))).astype(np.float32)
        h0 = (scale * rng.standard_normal(
            (h_rows, self.config.rank))).astype(np.float32)
        return w0, h0

    def _place_h0(self, h0: np.ndarray, w: int, cpb: int):
        """Scatter H blocks to their home workers (2-slice: worker-major
        (W, 2, cpb, K) so each worker starts with slice-A block w and slice-B
        block W+w)."""
        sess = self.session
        if self.config.num_slices == 2:
            return sess.scatter(np.ascontiguousarray(
                h0.reshape(2, w, cpb, -1).transpose(1, 0, 2, 3)))
        return sess.scatter(h0)

    def _prepare_sparse(self, rows, cols, vals, num_rows, num_cols, seed):
        cfg = self.config
        sess = self.session
        w = sess.num_workers
        n_blocks = cfg.num_slices * w
        if cfg.balance and len(rows):
            row_assign = serpentine_assign(
                np.bincount(rows, minlength=num_rows), w)
            col_assign = serpentine_assign(
                np.bincount(cols, minlength=num_cols), n_blocks)
        else:
            row_assign = identity_assign(num_rows, w)
            col_assign = identity_assign(num_cols, n_blocks)
        r_idx, c_idx, val, mask, rpw, cpb = bucketize(
            rows, cols, vals, w, num_rows, num_cols, cfg.minibatches_per_hop,
            num_col_blocks=n_blocks, row_assign=row_assign,
            col_assign=col_assign, validate=False)
        nnz = max(len(vals), 1)
        self.last_layout_stats = {
            "layout": "sparse", "padded": int(r_idx.size),
            "nnz": len(vals), "overhead": r_idx.size / nnz,
        }
        geom = (r_idx.shape[2],)

        rng = np.random.default_rng(seed)
        w0, h0 = self._init_factors(rng, w * rpw, n_blocks * cpb)
        return ("sparse", (sess.scatter(r_idx), sess.scatter(c_idx),
                           sess.scatter(val), sess.scatter(mask)),
                sess.scatter(w0), self._place_h0(h0, w, cpb),
                (num_rows, num_cols, row_assign, col_assign, rpw, cpb, geom))

    def _prepare_dense(self, rows, cols, vals, num_rows, num_cols, seed):
        cfg = self.config
        sess = self.session
        w = sess.num_workers
        nmb = cfg.minibatches_per_hop
        rpw, cpb, n_blocks = self._dense_geometry(num_rows, num_cols)
        row_assign = identity_assign(w * rpw, w)
        col_assign = identity_assign(num_cols, n_blocks)

        owner = rows // rpw
        r_loc = rows % rpw
        block = cols // cpb
        c_loc = cols % cpb
        # flat slab index within a worker: ((b * rpw) + r) * cpb + c
        flat = (block.astype(np.int64) * rpw + r_loc) * cpb + c_loc

        # group per worker, pad to a common capacity for the SPMD densify
        order = np.argsort(owner, kind="stable")
        counts = np.bincount(owner, minlength=w)
        cap = max(int(counts.max()), 1)
        idx_p = np.zeros((w, cap), np.int64)
        val_p = np.zeros((w, cap), np.float32)
        msk_p = np.zeros((w, cap), np.float32)
        starts = np.concatenate([[0], np.cumsum(counts)])
        fo, vo = flat[order], vals[order]
        for wi in range(w):
            lo, hi = starts[wi], starts[wi + 1]
            idx_p[wi, :hi - lo] = fo[lo:hi]
            val_p[wi, :hi - lo] = vo[lo:hi]
            msk_p[wi, :hi - lo] = 1.0

        slab_elems = n_blocks * rpw * cpb
        if slab_elems >= 2 ** 31:
            # device indices are int32 (jax x64 off): a bigger slab would
            # silently wrap and drop entries in the scatter
            raise ValueError(
                f"dense slab has {slab_elems} elements per worker (>= 2^31); "
                "use layout='sparse' or more workers")

        def densify(idx, val, msk):
            # scatter directly in bf16 — indices are unique (deduped in
            # prepare), so add == set and no f32 transient doubles the peak
            # memory that _choose_layout budgeted. Missing entries become NaN
            # (the mask slab is transient, freed after this program).
            idx, val, msk = idx[0], val[0], msk[0]
            bf = jnp.bfloat16
            v = jnp.zeros((slab_elems,), bf).at[idx].add(
                (val * msk).astype(bf))
            m = jnp.zeros((slab_elems,), bf).at[idx].add(msk.astype(bf))
            v = jnp.where(m > 0, v, jnp.asarray(jnp.nan, bf))
            return v.reshape((1, n_blocks, rpw, cpb))

        # one-shot prepare-time program, routed through session.run — the
        # documented build-and-invoke-once entry point (jaxlint JL103). It
        # still traces per prepare call (prepare runs once per layout);
        # programs that must keep their trace cache hold the session.spmd
        # callable instead.
        v_slab = sess.run(
            densify,
            sess.scatter(idx_p), sess.scatter(val_p), sess.scatter(msk_p),
            in_specs=(sess.shard(), sess.shard(), sess.shard()),
            out_specs=sess.shard(),
        )

        # regularizer counts (host): per-(worker, block, row) and
        # per-(worker, block, stripe, col)
        s_rows = rpw // nmb
        wb = owner.astype(np.int64) * n_blocks + block
        row_cnt = np.bincount(wb * rpw + r_loc,
                              minlength=w * n_blocks * rpw
                              ).reshape(w, n_blocks, rpw).astype(np.float32)
        stripe = r_loc // s_rows
        col_cnt = np.bincount((wb * nmb + stripe) * cpb + c_loc,
                              minlength=w * n_blocks * nmb * cpb
                              ).reshape(w, n_blocks, nmb, cpb
                                        ).astype(np.float32)

        self.last_layout_stats = {
            "layout": "dense", "padded": int(w) * slab_elems,
            "nnz": len(vals), "overhead": w * slab_elems / max(len(vals), 1),
        }
        geom = (nmb, rpw, cpb)

        rng = np.random.default_rng(seed)
        w0, h0 = self._init_factors(rng, w * rpw, n_blocks * cpb)
        return ("dense",
                (v_slab, sess.scatter(row_cnt), sess.scatter(col_cnt)),
                sess.scatter(w0), self._place_h0(h0, w, cpb),
                (num_rows, num_cols, row_assign, col_assign, rpw, cpb, geom))

    # -- training -------------------------------------------------------------- #

    def _finalize(self, out_w, out_h, meta):
        """Device factor blocks → (num_rows, K)/(num_cols, K) in original id
        order (undo the worker/block permutation)."""
        num_rows, num_cols, row_assign, col_assign, rpw, cpb = meta[:6]
        out_w = fetch(out_w)         # gathers sharded blocks across a gang
        out_h = fetch(out_h)
        if self.config.num_slices == 2:
            # (W, 2, cpb, K) worker-major → block-id-major (2W*cpb, K)
            w_, _, cpb_, k = out_h.shape
            out_h = out_h.transpose(1, 0, 2, 3).reshape(2 * w_ * cpb_, k)
        w_flat = out_w.reshape(-1, out_w.shape[-1])
        rb, rl = row_assign
        w_final = w_flat[rb[:num_rows].astype(np.int64) * rpw
                         + rl[:num_rows]]
        cb, cl = col_assign
        h_final = out_h[cb[:num_cols].astype(np.int64) * cpb + cl[:num_cols]]
        return w_final, h_final

    def train_prepared(self, state):
        """Run the compiled training program; factors stay ON DEVICE.

        Returns (w_dev, h_dev, rmse ndarray). The rmse fetch forces execution
        (tunnel platforms), but the factor blocks (MBs) are not transferred —
        this is the timing surface benchmarks use: steady-state epoch
        throughput, not the one-time D2H of the final model (bench.py,
        PERF.md). :meth:`fit_prepared` adds the fetch + de-permutation."""
        import time as _time

        layout, data, w0, h0, meta = state
        key = self._program(layout, self.config.minibatches_per_hop,
                            self.config.epochs, meta[6])
        t0 = _time.perf_counter()
        out_w, out_h, rmse = self._compiled[key](*data, w0, h0)
        rmse = np.asarray(rmse)
        # telemetry at the fetch that was already here: one event per epoch,
        # wall amortized over the scanned program (step_log docstring)
        telemetry.record_chunk(
            "sgd_mf", start=0, losses=rmse.tolist(),
            wall_s=_time.perf_counter() - t0,
            ledger=telemetry.ledger_for("sgd_mf", quant=self.config.quant))
        return out_w, out_h, rmse

    def fit_prepared(self, state) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run training on already-placed device data (no host prep)."""
        out_w, out_h, rmse = self.train_prepared(state)
        w_final, h_final = self._finalize(out_w, out_h, state[4])
        return w_final, h_final, rmse

    def fit_adaptive(self, state, tuner: Optional["HopBudgetTuner"] = None,
                     epochs: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                "HopBudgetTuner"]:
        """Train with an auto-tuned per-hop budget (reference:
        ``adjustMiniBatch``/``setTimer``, SGDCollectiveMapper.java:281-287).

        Runs one compiled epoch per host step, measures it, and lets the
        tuner pick the next budget among the divisors of
        ``minibatches_per_hop``. Programs for each budget are compiled once
        (ahead of the timed region) and reuse the same device data — the
        banded-shape property of the bucket padding."""
        import time as _time

        layout, data, w0, h0, meta = state
        geom = meta[6]
        nmb_fine = self.config.minibatches_per_hop
        cands = [d for d in range(1, nmb_fine + 1) if nmb_fine % d == 0]
        tuner = tuner or HopBudgetTuner(cands)
        epochs = epochs if epochs is not None else self.config.epochs
        w_cur, h_cur = w0, h0
        rmses = []
        for _ in range(epochs):
            nmb = tuner.next_budget()
            key = self._program(layout, nmb, 1, geom)
            if key not in self._warm:
                # AOT-compile outside the timed region and call the compiled
                # executable directly — the jit wrapper's dispatch cache is NOT
                # populated by lower().compile(), so calling the wrapper would
                # re-compile inside the timing. One throwaway call (outputs
                # discarded; the program is pure) absorbs first-execution
                # costs (e.g. executable upload on remote platforms).
                exe = self._compiled[key].lower(*data, w_cur, h_cur).compile()
                np.asarray(exe(*data, w_cur, h_cur)[2])
                self._warm[key] = exe
            fn = self._warm[key]
            t0 = _time.perf_counter()
            w_cur, h_cur, r = fn(*data, w_cur, h_cur)
            r = np.asarray(r)        # fetch forces execution (remote platforms)
            tuner.record(nmb, _time.perf_counter() - t0)
            rmses.append(r[0])
        w_final, h_final = self._finalize(w_cur, h_cur, meta)
        return w_final, h_final, np.asarray(rmses), tuner

    def warmup_epoch(self, state) -> None:
        """Compile + run the one-epoch program once, outputs discarded (the
        program is pure), so a subsequent timed ``fit_checkpointed`` region
        measures steady state rather than compilation."""
        layout, data, w0, h0, meta = state
        key = self._program(layout, self.config.minibatches_per_hop, 1,
                            meta[6])
        np.asarray(self._compiled[key](*data, w0, h0)[2])

    def fit_checkpointed(self, state, checkpointer, epochs: Optional[int] = None,
                         save_every: int = 1
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Train with periodic checkpointing and automatic resume.

        Runs one compiled epoch per host step (the fit_adaptive granularity);
        every ``save_every`` epochs the factor state is saved through
        ``checkpointer`` (utils.checkpoint.Checkpointer). If the checkpoint
        directory already holds state, training RESUMES from the newest epoch
        — a capability upgrade over the reference, which restarts from
        iteration 0 (SURVEY §5; KMUtil.storeCentroids saved final models
        only). Returns (W, H, rmse-per-epoch-run, first_epoch) where
        ``first_epoch`` is where this call started (0 for a fresh run).

        The training math is deterministic given (data, factors), so an
        interrupted + resumed run produces exactly the trajectory of an
        uninterrupted run at the same per-epoch program granularity.

        World-size-agnostic: the checkpoint stores the factors in this
        world's permuted block layout PLUS the (bin, slot) id maps and a
        manifest meta naming the writing world. Resuming under a different
        worker count (the supervisor's shrink/re-place relaunch) restores
        with the SAVED shapes and re-shards both factor tables onto this
        session's layout ON DEVICE (collectives.reshard: chunk-bounded
        all_to_all rounds, bitwise the numpy oracle, no host gather of a
        sharded leaf; ``SGDMFConfig.reshard`` selects the ring/host
        alternatives) — exact for every id the ratings reference,
        including across a 1-slice/2-slice layout change. Same-world
        resume takes the historical bitwise path untouched.
        """
        from harp_tpu.parallel import faults
        from harp_tpu.utils import checkpoint as ckpt_lib

        layout, data, w0, h0, meta = state
        num_rows, num_cols, row_assign, col_assign = meta[:4]
        geom = meta[6]
        nmb = self.config.minibatches_per_hop
        epochs = epochs if epochs is not None else self.config.epochs
        w_cur, h_cur = w0, h0
        start = 0
        world = self.session.num_workers
        # the id maps ride in every checkpoint so a DIFFERENT world can
        # de-permute to canonical id order (maps are deterministic given the
        # data, but only for the world that computed them)
        assign_leaves = {
            "row_bin": np.asarray(row_assign[0][:num_rows], np.int32),
            "row_slot": np.asarray(row_assign[1][:num_rows], np.int32),
            "col_bin": np.asarray(col_assign[0][:num_cols], np.int32),
            "col_slot": np.asarray(col_assign[1][:num_cols], np.int32),
        }
        # meta-less (pre-elastic) steps hold only the factor pair — restore
        # them through the legacy template so same-world resume of an old
        # work dir keeps working (a world CHANGE on one raises the clear
        # no-metadata error in _repartition_saved)
        legacy_like = {"w": np.zeros(w0.shape, w0.dtype),
                       "h": np.zeros(h0.shape, h0.dtype)}
        # verified resume, single read: manifest-checksummed steps only (a
        # corrupt newest checkpoint falls back to the previous step,
        # utils.checkpoint). `like` only conveys tree structure + dtypes:
        # host zeros, not a full (gang-collective) D2H gather of the
        # factors. A step written at another world size restores through a
        # template with the SAVED shapes (its manifest meta), then
        # re-partitions below.
        resume, saved, ck_meta = checkpointer.restore_latest_valid(
            like_from_meta=lambda m: (ckpt_lib.meta_like(m) if m
                                      else legacy_like),
            return_meta=True)
        if resume is not None:
            start = resume
            if ck_meta is not None and ck_meta.get("model") not in (
                    None, "sgd_mf"):
                # the template followed the SAVED shapes, so the leaf-count
                # guard cannot catch a wrong-model work dir anymore — the
                # recorded model name does
                raise ValueError(
                    f"checkpoint in this work dir was written by model "
                    f"{ck_meta['model']!r}, not sgd_mf — wrong work dir?")
            if start > epochs:
                raise ValueError(
                    f"checkpoint at epoch {start} exceeds the requested "
                    f"{epochs} epochs — the saved model is already trained "
                    f"past this budget (pass a fresh checkpoint directory "
                    f"or a larger epochs)")
            # shape equality is NOT world equality (64 rows block to 8x8 or
            # 4x16): trust the recorded world, fall back to shapes for
            # meta-less legacy steps
            if (int(ck_meta["world"]) != world if ck_meta
                    and "world" in ck_meta
                    else np.shape(saved["w"]) != tuple(w0.shape)):
                saved = self._repartition_saved(saved, ck_meta, state)
            # the device reshard path hands back already-placed arrays in
            # this session's sharding — no host round trip to undo
            w_cur = (saved["w"] if isinstance(saved["w"], jax.Array)
                     else jax.device_put(np.asarray(saved["w"]),
                                         w0.sharding))
            h_cur = (saved["h"] if isinstance(saved["h"], jax.Array)
                     else jax.device_put(np.asarray(saved["h"]),
                                         h0.sharding))
        key = self._program(layout, nmb, 1, geom)
        fn = self._compiled[key]
        rmses = []
        # telemetry: per-epoch step events at the existing np.asarray(r)
        # host sync (one epoch per host step here — real per-step timing)
        ledger = telemetry.ledger_for("sgd_mf", quant=self.config.quant)
        import time as _time

        for epoch in range(start, epochs):
            # iteration-boundary fault hook (parallel.faults)
            faults.fire(epoch + 1, checkpointer)
            t0 = _time.perf_counter()
            w_cur, h_cur, r = fn(*data, w_cur, h_cur)
            rmse_e = float(np.asarray(r)[0])
            wall = _time.perf_counter() - t0
            rmses.append(rmse_e)
            telemetry.record_chunk("sgd_mf", start=epoch, losses=[rmse_e],
                                   wall_s=wall, ledger=ledger)
            if (epoch + 1) % save_every == 0 or epoch + 1 == epochs:
                with telemetry.phase("sgd_mf.checkpoint"):
                    save_state = {"w": fetch(w_cur), "h": fetch(h_cur),
                                  **assign_leaves}
                    checkpointer.save(
                        epoch + 1, save_state,
                        meta=ckpt_lib.state_meta(
                            save_state, model="sgd_mf", world=world,
                            num_rows=num_rows, num_cols=num_cols,
                            num_slices=self.config.num_slices,
                            layout=layout))
        if hasattr(checkpointer, "wait"):
            checkpointer.wait()     # surface a failed async final write
        w_final, h_final = self._finalize(w_cur, h_cur, meta)
        return w_final, h_final, np.asarray(rmses), start

    def _reshard_mode(self) -> str:
        from harp_tpu.collectives import reshard as rs

        return rs.resolve_mode(self.config.reshard,
                               self.session.num_workers)

    def _repartition_saved(self, saved: dict, ck_meta: Optional[dict],
                           state) -> dict:
        """Factor state written at another world size → this session's
        layout. Default (``SGDMFConfig.reshard``): the DEVICE collective
        redistribution of collectives/reshard.py — the saved leaves go
        host→device once (the H2D any resume pays) and every row moves to
        its new (bin, slot) home in chunk-bounded all_to_all (or ring
        ppermute) rounds ON the mesh; no sharded leaf is ever gathered to
        host, and the returned leaves are device arrays already in this
        session's sharding. ``reshard="host"`` keeps the PR 8 numpy
        gather-and-resplit (collectives.repartition) as the parity oracle.
        Both paths are exact for every id the ratings reference; padded
        slots keep this run's fresh init (training math never reads them —
        their counts are zero, so neither gradients nor the regularizer
        move them). 2-slice layouts re-shard like 1-slice through the
        worker-major half-slice placement (reshard.block_layout), on
        either side of the resize. Run once at resume: the reshard step
        program is its own jaxlint-pinned trace target
        (reshard_factor_a2a/_ring); no collective is added to any TRAINING
        step program, so those budgets stay bitwise."""
        from harp_tpu.collectives import repartition as rep
        from harp_tpu.collectives import reshard as rs

        layout, data, w0, h0, meta = state
        num_rows, num_cols, row_assign, col_assign, rpw, cpb = meta[:6]
        if ck_meta is None or "world" not in ck_meta:
            raise ValueError(
                "checkpoint does not match this session's factor shapes and "
                "carries no world metadata (written by a pre-elastic "
                "version?) — resume at the original worker count")
        old_world = int(ck_meta["world"])
        old_ns = int(ck_meta.get("num_slices", 1))
        new_ns = self.config.num_slices
        if (int(ck_meta.get("num_rows", num_rows)) != num_rows
                or int(ck_meta.get("num_cols", num_cols)) != num_cols):
            raise ValueError(
                f"checkpoint was written for a "
                f"{ck_meta.get('num_rows')}x{ck_meta.get('num_cols')} "
                f"rating matrix; this run prepared {num_rows}x{num_cols} — "
                f"not the same dataset")
        w = self.session.num_workers
        saved_w = np.asarray(saved["w"])
        saved_h = np.asarray(saved["h"])
        old_rpw = saved_w.shape[0] // old_world
        # 2-slice checkpoints hold H as fetched: worker-major
        # (W_old, 2, cpb_old, K) — already flat device order when raveled
        old_cpb = (saved_h.shape[2] if saved_h.ndim == 4
                   else saved_h.shape[0] // (old_ns * old_world))
        old_w_lay = rs.block_layout(
            (np.asarray(saved["row_bin"]), np.asarray(saved["row_slot"])),
            old_rpw, old_world, 1)
        old_h_lay = rs.block_layout(
            (np.asarray(saved["col_bin"]), np.asarray(saved["col_slot"])),
            old_cpb, old_world, old_ns)
        new_w_lay = rs.block_layout(row_assign, rpw, w, 1)
        new_h_lay = rs.block_layout(col_assign, cpb, w, new_ns)
        mode = self._reshard_mode()
        if mode in ("device", "ring"):
            schedule = "alltoall" if mode == "device" else "ring"
            chunk = (self.config.reshard_chunk_bytes
                     or rs.DEFAULT_CHUNK_BYTES)
            w_new = rs.reshard_factor(
                self.session, saved_w, old_w_lay, old_world, new_w_lay,
                num_rows, w0, chunk_bytes=chunk, schedule=schedule)
            h_new = rs.reshard_factor(
                self.session, saved_h, old_h_lay, old_world, new_h_lay,
                num_cols, h0, chunk_bytes=chunk, schedule=schedule)
            return {**saved, "w": w_new, "h": h_new}
        # host oracle: bin-major flat arrays on both sides (2-slice device
        # order worker-major <-> bin-major via the half-slice transpose)
        def to_bin_major(a):
            return (a.transpose(1, 0, 2, 3).reshape(-1, a.shape[-1])
                    if a.ndim == 4 else a)

        def from_bin_major(flat, ns, w_, rpb):
            if ns == 1:
                return flat
            k = flat.shape[-1]
            return (flat.reshape(ns, w_, rpb, k).transpose(1, 0, 2, 3))

        fill_h = to_bin_major(fetch(h0))
        w_new = rep.repartition_factor(
            saved_w, (saved["row_bin"], saved["row_slot"]), old_rpw,
            row_assign, rpw, num_rows, fetch(w0))
        h_new = rep.repartition_factor(
            to_bin_major(saved_h),
            (saved["col_bin"], saved["col_slot"]), old_cpb,
            col_assign, cpb, num_cols, fill_h)
        return {**saved, "w": w_new,
                "h": from_bin_major(h_new, new_ns, w, cpb)}

    def fit(self, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
            num_rows: int, num_cols: int, seed: int = 0
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Train; returns (W (num_rows, K), H (num_cols, K), rmse-per-epoch)."""
        return self.fit_prepared(self.prepare(rows, cols, vals, num_rows,
                                              num_cols, seed))


class HopBudgetTuner:
    """Chooses the per-hop minibatch budget from measured epoch times.

    Policy (mirrors the intent of the reference's adaptive timer,
    SGDCollectiveMapper.adjustMiniBatch:623): more minibatches per hop =
    more sequential SGD steps = better convergence per epoch, but smaller
    device ops. Sweep each candidate once, then exploit the LARGEST budget
    whose time is within ``slack`` of the fastest, refining the estimate of
    the chosen budget with an EWMA each epoch."""

    def __init__(self, candidates, slack: float = 0.2):
        if not candidates:
            raise ValueError("need at least one candidate budget")
        self.candidates = sorted(set(int(c) for c in candidates))
        self.slack = slack
        self.times: dict = {}
        self._sweep = list(self.candidates)

    def next_budget(self) -> int:
        return self._sweep[0] if self._sweep else self.chosen

    @property
    def chosen(self) -> int:
        if not self.times:
            return self.candidates[-1]
        best = min(self.times.values())
        ok = [c for c in self.candidates
              if self.times.get(c, np.inf) <= best * (1 + self.slack)]
        return max(ok) if ok else self.candidates[-1]

    def record(self, budget: int, seconds: float) -> None:
        if self._sweep and self._sweep[0] == budget:
            self._sweep.pop(0)
        prev = self.times.get(budget)
        self.times[budget] = (seconds if prev is None
                              else 0.7 * prev + 0.3 * seconds)


def numpy_rmse(w_f: np.ndarray, h_f: np.ndarray, rows, cols, vals) -> float:
    pred = np.einsum("ij,ij->i", w_f[rows], h_f[cols])
    return float(np.sqrt(np.mean((vals - pred) ** 2)))
