"""SGD matrix factorization — the model-rotation flagship (Model B).

Reference parity: Harp's SGD-MF (ml/java sgd/SGDCollectiveMapper.java:54 and the
DAAL-2019 variant experimental/daal_sgd/SGDDaalCollectiveMapper.java:75 — BASELINE's
"harp-daal SGD-MF"). The reference design: rating rows are data-local, the item
factor matrix H is split into ``numModelSlices`` tables that ring-rotate among
workers (Rotator, dymoro/Rotator.java:30); within each rotation hop a timer-bounded
``Scheduler`` (dymoro/Scheduler.java:85-160) randomly schedules (row-split,
col-slice) blocks onto threads running asynchronous SGD point updates.

TPU-native re-expression:

* **Rotation** is a ``ppermute`` ring schedule (`collectives.rotation.rotate_scan`);
  after W hops every H block has visited every worker and is home again. The whole
  multi-epoch loop is ONE compiled XLA program.
* **The timer-bounded async scheduler** is host-driven and data-dependent — hostile
  to XLA (SURVEY §7 "hard parts"). Reformulated as **bounded staleness**: each hop
  runs a fixed number of mini-batch SGD steps over that (worker, block) bucket of
  ratings. Convergence-equivalent, not step-equivalent; Harp itself only claims
  statistical semantics for its racy Hogwild-style updates.
* **Sparsity** becomes static-shape bucketing: ratings are pre-sorted on the host
  into a (W workers × W column-blocks) grid of padded COO buckets, so the device
  program is fully static. Scatter-adds on factor rows use ``.at[].add`` which XLA
  lowers to efficient on-chip scatters; the inner dot products are batched on the
  MXU.

RMSE per epoch is accumulated on the fly (pre-update residuals) and combined with an
allreduce — the reference's test-RMSE allreduce (SGDCollectiveMapper.java:615-641).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.collectives import lax_ops, rotation
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class SGDMFConfig:
    """Mirrors the reference CLI (r, lambda, epsilon/lr, numIterations,
    numModelSlices → here the slice count is the worker count by construction)."""

    rank: int = 16
    lam: float = 0.05          # L2 regularization (reference: lambda)
    lr: float = 0.05           # learning rate (reference: epsilon)
    epochs: int = 10
    minibatches_per_hop: int = 4  # bounded-staleness stand-in for the dymoro timer
    num_slices: int = 1        # 2 = double-buffered pipeline (reference:
    #                            numModelSlices=2, dymoro comm/compute overlap)


def bucketize(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_workers: int,
    num_rows: int,
    num_cols: int,
    minibatches: int,
    num_col_blocks: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int, int]:
    """Host-side layout: COO ratings → (W, B, M) padded buckets.

    Bucket (w, b) holds the ratings whose row lives on worker w and whose column
    lives in H block b, with row/col indices localized to the block. This replaces
    the reference's regroup of VSets (SGDCollectiveMapper regroup-vw:384): the
    shuffle happens once on the host, the device program is static.
    ``num_col_blocks`` defaults to W (one H block per worker); the 2-slice
    pipeline uses 2W.
    """
    if len(rows):
        if rows.min() < 0 or rows.max() >= num_rows:
            raise ValueError(
                f"row indices must be in [0, {num_rows}); got "
                f"[{rows.min()}, {rows.max()}]")
        if cols.min() < 0 or cols.max() >= num_cols:
            raise ValueError(
                f"col indices must be in [0, {num_cols}); got "
                f"[{cols.min()}, {cols.max()}]")
    w = num_workers
    b_blocks = num_col_blocks or w
    rpw = -(-num_rows // w)        # rows per worker (ceil)
    cpb = -(-num_cols // b_blocks)  # cols per block
    owner = rows // rpw
    block = cols // cpb
    # One sort-based pass: order entries by (owner, block), then lay each bucket
    # out contiguously — O(nnz log nnz), not O(W^2 * nnz).
    bucket = owner.astype(np.int64) * b_blocks + block
    order = np.argsort(bucket, kind="stable")
    counts = np.bincount(bucket, minlength=w * b_blocks)
    m = max(int(counts.max()), 1) if counts.size else 1
    m = -(-m // minibatches) * minibatches   # pad so hops split evenly
    r_idx = np.zeros((w, b_blocks, m), np.int32)
    c_idx = np.zeros((w, b_blocks, m), np.int32)
    val = np.zeros((w, b_blocks, m), np.float32)
    mask = np.zeros((w, b_blocks, m), np.float32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    rs, cs, vs = rows[order], cols[order], vals[order]
    for b in range(w * b_blocks):
        lo, hi = starts[b], starts[b + 1]
        if lo == hi:
            continue
        wi, bi = divmod(b, b_blocks)
        k = hi - lo
        r_idx[wi, bi, :k] = rs[lo:hi] - wi * rpw
        c_idx[wi, bi, :k] = cs[lo:hi] - bi * cpb
        val[wi, bi, :k] = vs[lo:hi]
        mask[wi, bi, :k] = 1.0
    return r_idx, c_idx, val, mask, rpw, cpb


class SGDMF:
    """Distributed SGD matrix factorization over a HarpSession mesh."""

    def __init__(self, session: HarpSession, config: SGDMFConfig):
        self.session = session
        self.config = config
        self._compiled = {}       # (w, nmb, mbs) -> compiled SPMD program

    def _build(self, w: int, nmb: int, mbs: int):
        cfg = self.config
        lr, lam = cfg.lr, cfg.lam
        two_slice = cfg.num_slices == 2

        def fit_fn(r_idx, c_idx, val, mask, w0, h0):
            # Sharded bucket blocks arrive as (1, B, M): leading axis is this
            # worker's shard of the worker axis (B = num_slices * W).
            r_idx, c_idx, val, mask = r_idx[0], c_idx[0], val[0], mask[0]

            def update_bucket(w_local, h_block, sse, cnt, bucket_id):
                """Run the minibatched SGD updates of one (worker, block)
                bucket against the resident H block."""
                r = jnp.take(r_idx, bucket_id, axis=0).reshape(nmb, mbs)
                c = jnp.take(c_idx, bucket_id, axis=0).reshape(nmb, mbs)
                v = jnp.take(val, bucket_id, axis=0).reshape(nmb, mbs)
                msk = jnp.take(mask, bucket_id, axis=0).reshape(nmb, mbs)

                def mb_step(state, xs):
                    wl, hb, sse, cnt = state
                    rm, cm, vm, mm = xs
                    wr = wl[rm]                      # (mbs, K)
                    hc = hb[cm]
                    pred = jnp.sum(wr * hc, axis=-1)
                    err = (vm - pred) * mm
                    wl = wl.at[rm].add(
                        lr * (err[:, None] * hc - lam * wr * mm[:, None]))
                    hb = hb.at[cm].add(
                        lr * (err[:, None] * wr - lam * hc * mm[:, None]))
                    return (wl, hb, sse + jnp.sum(err * err),
                            cnt + jnp.sum(mm)), None

                (w_local, h_block, sse, cnt), _ = jax.lax.scan(
                    mb_step, (w_local, h_block, sse, cnt), (r, c, v, msk))
                return w_local, h_block, sse, cnt

            def hop_body(carry, h_block, t):
                w_local, sse, cnt = carry
                wid = lax_ops.worker_id()
                if two_slice:
                    # dymoro pipeline (Rotator, numModelSlices=2): resident
                    # slice s = t%2 has been shifted t//2 times; compute on it
                    # while the other slice's ppermute is in flight.
                    s = t % 2
                    src = (wid - t // 2) % w
                    bucket_id = s * w + src
                else:
                    bucket_id = (wid - t) % w       # home worker of resident
                w_local, h_block, sse, cnt = update_bucket(
                    w_local, h_block, sse, cnt, bucket_id)
                return (w_local, sse, cnt), h_block

            rotator = rotation.Rotator(w, cfg.num_slices)

            def epoch(state, _):
                w_local, h = state
                carry0 = (w_local, jnp.zeros(()), jnp.zeros(()))
                slices = h if two_slice else (h,)
                (w_local, sse, cnt), out = rotator.run(hop_body, carry0,
                                                       slices)
                h = out if two_slice else out[0]
                sse = jax.lax.psum(sse, lax_ops.WORKERS)
                cnt = jax.lax.psum(cnt, lax_ops.WORKERS)
                return (w_local, h), jnp.sqrt(sse / jnp.maximum(cnt, 1.0))

            # two-slice h0 arrives as this worker's (1, 2, cpb, K) chunk:
            # slice A block w and slice B block W+w
            h_init = (h0[0, 0], h0[0, 1]) if two_slice else h0
            (w_local, h_fin), rmse = jax.lax.scan(
                epoch, (w0, h_init), None, length=cfg.epochs)
            if two_slice:
                h_fin = jnp.stack(h_fin, axis=0)[None]   # (1, 2, cpb, K)
            return w_local, h_fin, rmse

        sess = self.session
        return sess.spmd(
            fit_fn,
            in_specs=(sess.shard(), sess.shard(), sess.shard(), sess.shard(),
                      sess.shard(), sess.shard()),
            out_specs=(sess.shard(), sess.shard(), sess.replicate()),
        )

    def prepare(self, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                num_rows: int, num_cols: int, seed: int = 0):
        """Bucketize + place data and init factors on the mesh ONCE.

        Returns an opaque state tuple for :meth:`fit_prepared` — keeps host
        prep and H2D transfer out of timed regions (KMeans.prepare idiom)."""
        cfg = self.config
        if cfg.num_slices not in (1, 2):
            raise ValueError("num_slices must be 1 or 2")
        sess = self.session
        w = sess.num_workers
        n_blocks = cfg.num_slices * w
        r_idx, c_idx, val, mask, rpw, cpb = bucketize(
            rows, cols, vals, w, num_rows, num_cols, cfg.minibatches_per_hop,
            num_col_blocks=n_blocks)
        m = r_idx.shape[2]
        nmb = cfg.minibatches_per_hop
        mbs = m // nmb
        key = (w, nmb, mbs, cfg.num_slices)
        if key not in self._compiled:
            self._compiled[key] = self._build(w, nmb, mbs)

        rng = np.random.default_rng(seed)
        scale = 1.0 / np.sqrt(cfg.rank)
        w0 = (scale * rng.standard_normal((w * rpw, cfg.rank))).astype(np.float32)
        h0 = (scale * rng.standard_normal(
            (n_blocks * cpb, cfg.rank))).astype(np.float32)
        if cfg.num_slices == 2:
            # global block b = s*W + w' → worker w' holds (slice s, block w'):
            # lay out worker-major (W, 2, cpb, K) so scatter gives each worker
            # its two resident blocks
            h0_dev = sess.scatter(np.ascontiguousarray(
                h0.reshape(2, w, cpb, cfg.rank).transpose(1, 0, 2, 3)))
        else:
            h0_dev = sess.scatter(h0)
        return (key, sess.scatter(r_idx), sess.scatter(c_idx),
                sess.scatter(val), sess.scatter(mask), sess.scatter(w0),
                h0_dev, num_rows, num_cols)

    def fit_prepared(self, state) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run training on already-placed device data (no host prep)."""
        key, r_idx, c_idx, val, mask, w0, h0, num_rows, num_cols = state
        out_w, out_h, rmse = self._compiled[key](r_idx, c_idx, val, mask, w0,
                                                 h0)
        out_h = np.asarray(out_h)
        if key[3] == 2:
            # (W, 2, cpb, K) worker-major → block-id-major (2W*cpb, K)
            w_, _, cpb, k = out_h.shape
            out_h = out_h.transpose(1, 0, 2, 3).reshape(2 * w_ * cpb, k)
        return (np.asarray(out_w)[:num_rows], out_h[:num_cols],
                np.asarray(rmse))

    def fit(self, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
            num_rows: int, num_cols: int, seed: int = 0
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Train; returns (W (num_rows, K), H (num_cols, K), rmse-per-epoch)."""
        return self.fit_prepared(self.prepare(rows, cols, vals, num_rows,
                                              num_cols, seed))


def numpy_rmse(w_f: np.ndarray, h_f: np.ndarray, rows, cols, vals) -> float:
    pred = np.einsum("ij,ij->i", w_f[rows], h_f[cols])
    return float(np.sqrt(np.mean((vals - pred) ** 2)))
