"""Alternating least squares — explicit and implicit (confidence-weighted).

Reference parity: daal_als (ALSDaalCollectiveMapper.java:49 — implicit ALS on CSR
with DAAL's 4 distributed train steps; Harp allgather:336 + bcast of step2/step3
partial results:396-490) and daal_als_batch.

TPU-native: the factor matrices stay REPLICATED between half-iterations (they are
small: entities × rank); each half-iteration a worker solves the normal equations
for its shard of users (then items) as one batched Cholesky solve on the MXU, and
one all_gather re-replicates the updated factor — DAAL's step1-4 dance collapses
to "batched local solve + allgather".

Duplicate (row, col) pairs are dropped (keep-first) in ``prepare`` for BOTH
layouts so the two paths always train on the identical entry set (the
sgd_mf contract); the count is in ``last_layout_stats["duplicates_dropped"]``.

Dual layout (the dense-SGD-MF pattern applied to ALS): ``layout="dense"``
stores the rating matrix as NaN-encoded bf16 planes and computes each side's
normal equations as two big GEMMs (conf @ VV and a weighted @ V) instead of
per-entry factor-row gathers (128-byte granules, the TPU sparse-access wall);
auto-selected when both planes fit HARP_ALS_DENSE_MAX_BYTES. Either way the
batched k×k solve dominates on TPU — see ALSConfig.solver for the measured
story.

Sparse layout (SURVEY §7 recipe, skew-robust): ragged observed-entry lists become
**capped chunks** — a row's entries split into chunks of at most
``chunk_factor × mean`` entries, each chunk computing a partial Gram/RHS that a
``segment_sum`` combines per row before the solve. A Zipf head row therefore
costs proportionally more chunks instead of inflating every row's padding
(the round-1 ``pad_csr_lists`` padded all rows to the global max row length);
rows are dealt to workers by balanced (serpentine-LPT) entry counts. The
reference ingested exactly such power-law CSR data
(HarpDAALDataSource.regroupCOOList:399).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu import telemetry
from harp_tpu.collectives import lax_ops
from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    lam: float = 0.1            # L2 (DAAL: lambda)
    alpha: float = 40.0         # implicit confidence weight (DAAL: alpha)
    iterations: int = 10
    implicit: bool = True
    balance: bool = True        # serpentine-LPT row→worker assignment
    chunk_factor: float = 2.0   # chunk cap = ceil(chunk_factor * mean entries)
    solver: str = "auto"        # auto | pallas | cholesky | newton — how the
    #   batched k×k SPD normal equations are solved. The solve DOMINATED ALS
    #   on TPU through r3 (measured ablation, PERF.md: the bench iteration
    #   was 70 ms with the solve and 9.6 ms without): XLA's batched-solve
    #   lowering serializes on k and underfills the MXU, so Cholesky ≈
    #   Newton–Schulz ≈ 30 ms per (8192, 32, 32)-batch solve pair despite
    #   the solve being only ~180 MFLOP. "pallas" is the r4 fix — a
    #   lane-vectorized batched Cholesky (ops/pallas_kernels.spd_solve_pallas:
    #   batch on the 128-lane axis, unrolled outer-product factorization +
    #   substitutions, pure full-width VPU work) that makes the solve
    #   HBM-bound. "auto" = pallas on TPU at k ≤ 64, else cholesky (exact
    #   XLA path); "newton" (pure batched GEMMs, Precision.HIGHEST — TPU's
    #   default bf16 multiply floors its quadratic convergence at ~1e-1) is
    #   kept as the measured alternative.
    newton_iters: int = 30
    layout: str = "auto"        # auto | dense | sparse — "dense" stores the
    #   rating matrix as NaN-encoded bf16 planes and computes each side's
    #   normal equations as two big GEMMs (conf @ VV and weighted @ V): the
    #   sparse path's factor-row gathers are 128 B granules (~25M rows/s,
    #   the same wall dense SGD-MF hit), while the dense A-GEMM runs the
    #   MXU at matrix-matrix rates. NOTE the bf16 planes QUANTIZE the stored
    #   ratings to ~3 significant digits (8-bit mantissa: integer counts
    #   above 256 and finely-graded explicit ratings round) — fine for
    #   implicit confidence weights, a real numeric change for explicit
    #   regression targets. "auto" therefore picks dense only in IMPLICIT
    #   mode (when this worker's plane share fits dense_max_bytes) and
    #   keeps explicit-rating runs on the exact f32 sparse path; request
    #   layout="dense" explicitly to accept the quantization there
    dense_max_bytes: int = 2 * 1024 ** 3  # per-WORKER budget for the two
    #   bf16 plane shards (the SGDMFConfig.dense_max_bytes convention)
    ablate_solve: bool = False  # timing ablation ONLY (r10, the ALS stage
    #   budget bench row): skip the batched k×k SPD solve — x = b rides
    #   through identity — so bench.py can price the solve stage by
    #   difference (the r3/r4 PERF ablation, now a reproducible row instead
    #   of a one-off). Results are WRONG; never use outside timing.


def pad_csr_lists(rows, cols, vals, num_rows, num_workers):
    """(entity → padded neighbor list): idx (R_pad, M), val (R_pad, M), mask.

    Round-1 layout (pads every row to the global max row length) — kept for
    callers with uniform data; ALS itself uses :func:`pad_csr_chunks`."""
    order = np.argsort(rows, kind="stable")
    r, c, v = rows[order], cols[order], vals[order]
    rpw = -(-num_rows // num_workers)
    r_pad = rpw * num_workers
    counts = np.bincount(r, minlength=r_pad)
    m = max(int(counts.max()), 1)
    idx = np.zeros((r_pad, m), np.int32)
    val = np.zeros((r_pad, m), np.float32)
    mask = np.zeros((r_pad, m), np.float32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(len(r)) - starts[r]          # slot within each row bucket
    idx[r, pos] = c
    val[r, pos] = v
    mask[r, pos] = 1.0
    return idx, val, mask


def pad_csr_chunks(rows, cols, vals, num_rows, num_workers,
                   chunk_factor: float = 2.0, balance: bool = True):
    """Skew-robust CSR layout: capped chunks + per-row segment ids.

    Returns (idx (W, NC, C), val, mask, chunk_row (W, NC) local row slot,
    (row_bin, row_slot), rpw, stats). Padded chunks point at slot 0 with an
    all-zero mask.
    """
    from harp_tpu.models.sgd_mf import identity_assign, serpentine_assign

    nnz = len(rows)
    counts_global = np.bincount(rows, minlength=num_rows)
    if balance and nnz:
        row_bin, row_slot = serpentine_assign(counts_global, num_workers)
    else:
        row_bin, row_slot = identity_assign(num_rows, num_workers)
    rpw = -(-num_rows // num_workers)
    cap = max(1, int(np.ceil(chunk_factor * max(nnz, 1)
                             / max(num_rows, 1))))
    # order entries by (worker, row slot); chunks are consecutive runs of cap
    owner = row_bin[rows]
    slot = row_slot[rows]
    order = np.lexsort((slot, owner))
    o_own, o_slot = owner[order], slot[order]
    o_cols, o_vals = cols[order], vals[order]
    # position of each entry within its row  →  chunk id within the row
    row_key = o_own.astype(np.int64) * rpw + o_slot
    starts = np.concatenate([[0], np.cumsum(np.bincount(
        row_key, minlength=num_workers * rpw))])
    pos_in_row = np.arange(nnz) - starts[row_key]
    chunk_of_entry = pos_in_row // cap
    pos_in_chunk = pos_in_row % cap
    # number the chunks per worker
    n_chunks_per_row = -(-counts_global // cap)      # per global row id
    chunks_per_worker = np.zeros(num_workers, np.int64)
    np.add.at(chunks_per_worker, row_bin, n_chunks_per_row)
    nc = max(int(chunks_per_worker.max()), 1)
    # chunk index within worker: cumulative chunks of earlier slots + chunk id
    chunk_base = np.zeros((num_workers, rpw), np.int64)
    np.add.at(chunk_base, (row_bin, row_slot), n_chunks_per_row)
    chunk_base = np.cumsum(chunk_base, axis=1) - chunk_base
    entry_chunk = chunk_base[o_own, o_slot] + chunk_of_entry

    idx = np.zeros((num_workers, nc, cap), np.int32)
    val = np.zeros((num_workers, nc, cap), np.float32)
    mask = np.zeros((num_workers, nc, cap), np.float32)
    chunk_row = np.zeros((num_workers, nc), np.int32)
    idx[o_own, entry_chunk, pos_in_chunk] = o_cols
    val[o_own, entry_chunk, pos_in_chunk] = o_vals
    mask[o_own, entry_chunk, pos_in_chunk] = 1.0
    chunk_row[o_own, entry_chunk] = o_slot
    stats = {"padded": int(idx.size), "nnz": nnz,
             "overhead": idx.size / max(nnz, 1), "chunk_cap": cap}
    return idx, val, mask, chunk_row, (row_bin, row_slot), rpw, stats


def _resolve_solver(cfg: ALSConfig) -> str:
    if cfg.solver not in ("auto", "pallas", "cholesky", "newton"):
        raise ValueError(f"solver must be auto|pallas|cholesky|newton, got "
                         f"{cfg.solver!r}")
    if cfg.solver != "auto":
        return cfg.solver
    from harp_tpu.ops.pallas_kernels import use_spd_solve_pallas

    # measured on v5e (PERF.md r4): the lane-vectorized pallas Cholesky
    # breaks the XLA batched-solve plateau; where it doesn't apply,
    # cholesky ties or beats newton at every batch shape tried and is exact
    return "pallas" if use_spd_solve_pallas(cfg.rank) else "cholesky"


def _spd_solve(a, b, cfg: ALSConfig):
    """Solve the batched SPD systems ``a @ x = b`` (a: (N, K, K), b: (N, K)).

    newton: X_{t+1} = X_t (2I − A X_t) from X_0 = I / ||A||_inf — for SPD A
    the row-sum norm bounds λ_max, so ||I − X_0 A||_2 = 1 − λ_min/||A||_inf
    < 1 and the error squares every round: ~log2(cond) + 5 rounds reach f32
    accuracy (30 rounds cover cond ≤ ~3e7; ALS regularizes with λI so cond
    ≤ λ_max/λ). Every op is a batched GEMM — but measured on v5e this buys
    nothing over Cholesky: batched (8192, 32, 32) operands underfill the
    MXU for both, ~30 ms per solve pair either way (ALSConfig.solver note,
    PERF.md r3). Kept as the measured alternative and for platforms where
    batched triangular solves lower worse."""
    if cfg.ablate_solve:
        # stage-budget ablation: keep A's construction live (consume it so
        # XLA cannot dead-code the gram/normal-equation stages) but skip
        # the solve itself — identity plus a free first-column touch
        return b + 0.0 * a[..., 0]
    solver = _resolve_solver(cfg)
    if solver == "pallas":
        from harp_tpu.ops import pallas_kernels

        if not pallas_kernels._HAVE_PALLAS:
            raise ValueError(
                "solver='pallas' requires jax.experimental.pallas; use "
                "solver='cholesky' (or 'auto') on this platform")
        # explicit request off-TPU runs the kernel in interpret mode (slow
        # but exact — the path CI and the CPU mesh exercise); 'auto' never
        # resolves here off-TPU
        interpret = jax.default_backend() != "tpu"
        return pallas_kernels.spd_solve_pallas(a, b, interpret=interpret)
    if solver == "cholesky":
        return jax.scipy.linalg.solve(a, b[..., None], assume_a="pos")[..., 0]
    k = a.shape[-1]
    eye = jnp.eye(k, dtype=a.dtype)
    norminf = jnp.max(jnp.sum(jnp.abs(a), axis=-1), axis=-1)
    x = (1.0 / norminf)[..., None, None] * eye
    # full f32 multiply precision is LOAD-BEARING here: TPU's default
    # bf16-multiply f32 matmul floors the NS error at ~1e-1 (measured — the
    # iteration squares its error each round, so truncation noise persists)
    hi = jax.lax.Precision.HIGHEST

    def step(x, _):
        ax = jnp.matmul(a, x, precision=hi)
        x = jnp.matmul(x, 2.0 * eye - ax, precision=hi)
        return x, ()

    x, _ = jax.lax.scan(step, x, None, length=cfg.newton_iters)
    return jnp.matmul(x, b[..., None], precision=hi)[..., 0]


def _half_step(factor_other, idx, val, mask, chunk_row, rpw: int,
               cfg: ALSConfig):
    """Solve this worker's block of one side's normal equations.

    factor_other: replicated (E_other, K) in the OTHER side's permuted slot
    order (idx entries are pre-remapped on the host). idx/val/mask:
    (NC, C) capped chunks; chunk_row: (NC,) local row slot per chunk.
    Returns the updated local block (rpw, K)."""
    k = cfg.rank
    vi = factor_other[idx] * mask[..., None]     # (NC, C, K)
    if cfg.implicit:
        # Hu, Koren, Volinsky: A = V'V + V'(C−I)V + λI;  b = V'C·p (p=1 observed)
        conf = cfg.alpha * val * mask            # c − 1
        a_part = jnp.einsum("cmk,cm,cml->ckl", vi, conf, vi)
        b_part = jnp.einsum("cmk,cm->ck", vi, (1.0 + conf) * mask)
    else:
        # explicit: normal equations over observed entries only
        a_part = jnp.einsum("cmk,cml->ckl", vi, vi)
        b_part = jnp.einsum("cmk,cm->ck", vi, val * mask)
    a = jax.ops.segment_sum(a_part, chunk_row, num_segments=rpw)
    b = jax.ops.segment_sum(b_part, chunk_row, num_segments=rpw)
    if cfg.implicit:
        gram = jax.lax.dot_general(              # V'V over ALL entities
            factor_other, factor_other, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        a = a + gram[None]
    a = a + cfg.lam * jnp.eye(k, dtype=a.dtype)[None]
    return _spd_solve(a, b, cfg)


def _train(u_data, i_data, u0, v0, u_rpw: int, i_rpw: int, cfg: ALSConfig,
           axis_name: str = WORKERS):
    u_idx, u_val, u_mask, u_crow = u_data
    i_idx, i_val, i_mask, i_crow = i_data

    def iteration(carry, _):
        u, v = carry                             # both replicated (E, K)
        # users half-step: local block solve, then re-replicate
        u_block = _half_step(v, u_idx, u_val, u_mask, u_crow, u_rpw, cfg)
        u = lax_ops.allgather(u_block, axis_name)
        v_block = _half_step(u, i_idx, i_val, i_mask, i_crow, i_rpw, cfg)
        v = lax_ops.allgather(v_block, axis_name)
        # monitor: squared error on observed entries of the user-side chunks
        pred = jnp.einsum("cmk,ck->cm", v[u_idx] * u_mask[..., None],
                          u_block[u_crow])
        tgt = u_val if not cfg.implicit else (u_mask * 1.0)
        sse = jax.lax.psum(jnp.sum(u_mask * (tgt - pred) ** 2), axis_name)
        cnt = jax.lax.psum(jnp.sum(u_mask), axis_name)
        return (u, v), jnp.sqrt(sse / jnp.maximum(cnt, 1.0))

    (u, v), rmse = jax.lax.scan(iteration, (u0, v0), None,
                                length=cfg.iterations)
    return u, v, rmse


# --------------------------------------------------------------------------- #
# Dense layout: normal equations as GEMMs (the dense-SGD-MF trick for ALS)
# --------------------------------------------------------------------------- #

def _half_step_dense(factor_other, val_plane, rpw: int, cfg: ALSConfig):
    """One side's normal equations from a dense NaN-encoded value plane.

    ``val_plane``: (rpw, E_other) bf16, NaN = unobserved (0 is a VALID
    observed value in explicit mode). A_u = Σ_i w_ui v_i v_iᵀ collapses to
    one (rpw, E) @ (E, K²) GEMM against the factor's row-wise outer products
    — MXU matrix-matrix rates instead of 128-byte row gathers. bf16 operands,
    f32 accumulation (the dense SGD-MF precision contract)."""
    k = cfg.rank
    obs = jnp.isfinite(val_plane)
    vz = jnp.where(obs, val_plane, 0).astype(jnp.bfloat16)
    f_b = factor_other.astype(jnp.bfloat16)
    e = factor_other.shape[0]
    vv = (f_b[:, :, None] * f_b[:, None, :]).reshape(e, k * k)
    f32 = jnp.float32
    if cfg.implicit:
        # Hu-Koren: A = V'V + V'(C−I)V + λI, C−I = alpha*r on observed
        conf = (cfg.alpha * vz).astype(jnp.bfloat16)
        a = jax.lax.dot_general(conf, vv, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
        gram = jax.lax.dot_general(factor_other, factor_other,
                                   (((0,), (0,)), ((), ())),
                                   preferred_element_type=f32)
        a = a.reshape(rpw, k, k) + gram[None]
        bw = jnp.where(obs, 1.0 + cfg.alpha * vz.astype(f32), 0.0)
        b = jax.lax.dot_general(bw.astype(jnp.bfloat16), f_b,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    else:
        a = jax.lax.dot_general(obs.astype(jnp.bfloat16), vv,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
        a = a.reshape(rpw, k, k)
        b = jax.lax.dot_general(vz, f_b, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    a = a + cfg.lam * jnp.eye(k, dtype=a.dtype)[None]
    return _spd_solve(a, b, cfg)


def _train_dense(u_plane, i_plane, u0, v0, u_rpw: int, i_rpw: int,
                 cfg: ALSConfig, axis_name: str = WORKERS):
    """Dense-layout training loop: same allgather choreography as _train,
    with the dense half-step and a GEMM-based RMSE monitor."""

    def iteration(carry, _):
        u, v = carry
        u_block = _half_step_dense(v, u_plane, u_rpw, cfg)
        u = lax_ops.allgather(u_block, axis_name)
        v_block = _half_step_dense(u, i_plane, i_rpw, cfg)
        v = lax_ops.allgather(v_block, axis_name)
        obs = jnp.isfinite(u_plane)
        pred = jax.lax.dot_general(
            u_block.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        tgt = (jnp.where(obs, u_plane, 0).astype(jnp.float32)
               if not cfg.implicit else 1.0)
        sse = jax.lax.psum(jnp.sum(jnp.where(obs, (tgt - pred) ** 2, 0.0)),
                           axis_name)
        cnt = jax.lax.psum(jnp.sum(obs.astype(jnp.float32)), axis_name)
        return (u, v), jnp.sqrt(sse / jnp.maximum(cnt, 1.0))

    (u, v), rmse = jax.lax.scan(iteration, (u0, v0), None,
                                length=cfg.iterations)
    return u, v, rmse


class ALS:
    """Distributed ALS over a HarpSession mesh (daal_als parity)."""

    def __init__(self, session: HarpSession, config: ALSConfig):
        self.session = session
        self.config = config
        self._fns = {}
        self.last_layout_stats: dict = {}

    def prepare(self, rows, cols, vals, num_users: int, num_items: int,
                seed: int = 0):
        """Host layout + H2D ONCE; returns an opaque state for
        :meth:`fit_prepared` (the KMeans/SGDMF prepare idiom — keeps host
        prep and transfers out of timed regions)."""
        from harp_tpu.models.sgd_mf import _validate_coo

        sess, cfg = self.session, self.config
        w = sess.num_workers
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals, np.float32)
        _validate_coo(rows, cols, num_users, num_items, vals)  # incl. NaN
        if cfg.implicit and len(vals) and not (vals.min() >= 0):
            # Hu-Koren confidence c = 1 + alpha*r assumes r >= 0 (interaction
            # counts); a negative r can make the normal equations indefinite
            # and the Cholesky solve silently produce NaNs
            raise ValueError(
                "implicit ALS requires nonnegative interaction values "
                f"(confidence counts); got min {vals.min():.4f} — use "
                "implicit=False for signed ratings, or feed counts")
        # keep-first dedupe for BOTH layouts so they train on the identical
        # entry set (shared sgd_mf.dedupe_coo contract; the sparse path
        # would otherwise SUM duplicates while the dense plane kept one)
        from harp_tpu.models.sgd_mf import dedupe_coo

        rows, cols, vals, self._duplicates_dropped = dedupe_coo(
            rows, cols, vals, num_items)
        if self._pick_layout(num_users, num_items) == "dense":
            return self._prepare_dense(rows, cols, vals, num_users,
                                       num_items, seed)
        u_layout = pad_csr_chunks(rows, cols, vals, num_users, w,
                                  cfg.chunk_factor, cfg.balance)
        i_layout = pad_csr_chunks(cols, rows, vals, num_items, w,
                                  cfg.chunk_factor, cfg.balance)
        u_idx, u_val, u_mask, u_crow, u_assign, u_rpw, u_stats = u_layout
        i_idx, i_val, i_mask, i_crow, i_assign, i_rpw, i_stats = i_layout
        self.last_layout_stats = {
            "layout": "sparse",
            "users": u_stats, "items": i_stats,
            "overhead": max(u_stats["overhead"], i_stats["overhead"]),
            "duplicates_dropped": self._duplicates_dropped,
        }
        # chunk idx entries address the OTHER side's replicated factor, which
        # lives in permuted slot order after allgather — remap on the host
        ib, isl = i_assign
        u_idx = (ib[u_idx].astype(np.int64) * i_rpw + isl[u_idx]).astype(np.int32)
        ub, usl = u_assign
        i_idx = (ub[i_idx].astype(np.int64) * u_rpw + usl[i_idx]).astype(np.int32)

        rng = np.random.default_rng(seed)
        scale = 1.0 / np.sqrt(cfg.rank)
        u0 = (scale * rng.random((w * u_rpw, cfg.rank))).astype(np.float32)
        v0 = (scale * rng.random((w * i_rpw, cfg.rank))).astype(np.float32)
        # zero phantom padding slots: the implicit-mode gram V'V sums over ALL
        # rows of the replicated factor, so random init there would bias the
        # first half-iteration's normal equations
        u_slots = ub.astype(np.int64)[:num_users] * u_rpw + usl[:num_users]
        v_slots = ib.astype(np.int64)[:num_items] * i_rpw + isl[:num_items]
        used_u = np.zeros(w * u_rpw, bool)
        used_u[u_slots] = True
        u0[~used_u] = 0.0
        used_v = np.zeros(w * i_rpw, bool)
        used_v[v_slots] = True
        v0[~used_v] = 0.0

        key = (u_idx.shape, i_idx.shape, u_rpw, i_rpw)
        if key not in self._fns:
            self._fns[key] = sess.spmd(
                lambda a, b, c, d, e, f, g, h, i, j: _train(
                    (a[0], b[0], c[0], d[0]), (e[0], f[0], g[0], h[0]),
                    i, j, u_rpw, i_rpw, cfg),
                in_specs=(sess.shard(),) * 8 + (sess.replicate(),) * 2,
                out_specs=(sess.replicate(),) * 3)
        placed = (sess.scatter(u_idx), sess.scatter(u_val),
                  sess.scatter(u_mask), sess.scatter(u_crow),
                  sess.scatter(i_idx), sess.scatter(i_val),
                  sess.scatter(i_mask), sess.scatter(i_crow),
                  sess.replicate_put(u0), sess.replicate_put(v0))
        return key, placed, u_slots, v_slots

    def _pick_layout(self, num_users: int, num_items: int) -> str:
        cfg = self.config
        if cfg.layout not in ("auto", "dense", "sparse"):
            raise ValueError(f"layout must be auto|dense|sparse, got "
                             f"{cfg.layout!r}")
        if cfg.layout != "auto":
            return cfg.layout
        if not cfg.implicit:
            # bf16 planes quantize explicit training targets (see the
            # ALSConfig.layout note) — auto never changes results silently
            return "sparse"
        w = self.session.num_workers
        u_rpw = -(-num_users // w)
        i_rpw = -(-num_items // w)
        # each worker holds one (u_rpw, i_pad) and one (i_rpw, u_pad) bf16
        # shard — the budget is per-worker HBM, so dense stays available on
        # big meshes where the global planes dwarf a single chip
        per_worker = (u_rpw * (i_rpw * w) + i_rpw * (u_rpw * w)) * 2
        return "dense" if per_worker <= cfg.dense_max_bytes else "sparse"

    def _prepare_dense(self, rows, cols, vals, num_users: int,
                       num_items: int, seed: int):
        """Dense NaN-encoded plane layout (see ALSConfig.layout). Entries
        arrive already deduped (keep-first, prepare's contract). Factor rows
        stay in natural entity order (no slot permutation); padding rows sit
        past num_users/num_items and are zeroed so the implicit gram V'V is
        unbiased."""
        import ml_dtypes

        sess, cfg = self.session, self.config
        w = sess.num_workers
        u_rpw = -(-num_users // w)
        i_rpw = -(-num_items // w)
        u_pad, i_pad = w * u_rpw, w * i_rpw
        # build straight in bf16 (host peak = exactly the budgeted bytes);
        # entries are already deduped, and the item plane is the transpose
        # by construction — no second fill pass
        u_plane = np.full((u_pad, i_pad), np.nan, ml_dtypes.bfloat16)
        u_plane[rows, cols] = vals.astype(ml_dtypes.bfloat16)
        i_plane = np.ascontiguousarray(u_plane.T)
        self.last_layout_stats = {
            "layout": "dense",
            "plane_bytes": 2 * u_pad * i_pad * 2,
            "duplicates_dropped": self._duplicates_dropped,
            "overhead": (u_pad * i_pad) / max(len(rows), 1),
        }
        rng = np.random.default_rng(seed)
        scale = 1.0 / np.sqrt(cfg.rank)
        u0 = (scale * rng.random((u_pad, cfg.rank))).astype(np.float32)
        v0 = (scale * rng.random((i_pad, cfg.rank))).astype(np.float32)
        u0[num_users:] = 0.0
        v0[num_items:] = 0.0
        key = ("dense", u_rpw, i_rpw, w, cfg.implicit)
        if key not in self._fns:
            self._fns[key] = sess.spmd(
                lambda up, ip, u, v: _train_dense(up, ip, u, v, u_rpw,
                                                  i_rpw, cfg),
                in_specs=(sess.shard(), sess.shard(),
                          sess.replicate(), sess.replicate()),
                out_specs=(sess.replicate(),) * 3)
        placed = (sess.scatter(jnp.asarray(u_plane, jnp.bfloat16)),
                  sess.scatter(jnp.asarray(i_plane, jnp.bfloat16)),
                  sess.replicate_put(u0), sess.replicate_put(v0))
        return (key, placed, np.arange(num_users), np.arange(num_items))

    def train_prepared(self, state):
        """Run the compiled train program; factors stay ON DEVICE. Returns
        (u_dev, v_dev, rmse ndarray) — the benchmark timing surface (the
        rmse fetch forces execution; the factor D2H is a one-time cost)."""
        import time as _time

        key, placed, _, _ = state
        t0 = _time.perf_counter()
        u, v, rmse = self._fns[key](*placed)
        rmse = np.asarray(rmse)
        # telemetry at the rmse fetch that was already here (per-iteration
        # events, wall amortized over the scanned program); the manifest row
        # pins the explicit path only — implicit jobs get no comm row
        telemetry.record_chunk(
            "als", start=0, losses=rmse.tolist(),
            wall_s=_time.perf_counter() - t0,
            ledger=(telemetry.ledger_for("als")
                    if not self.config.implicit else None))
        return u, v, rmse

    def fit_prepared(self, state
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the compiled train program on prepared state; returns
        (U (num_users, K), V (num_items, K), rmse-per-iteration)."""
        u, v, rmse = self.train_prepared(state)
        _, _, u_slots, v_slots = state
        u_final = np.asarray(u)[u_slots]
        v_final = np.asarray(v)[v_slots]
        return u_final, v_final, rmse

    def fit(self, rows, cols, vals, num_users: int, num_items: int,
            seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (U (num_users, K), V (num_items, K), rmse-per-iteration)."""
        return self.fit_prepared(self.prepare(rows, cols, vals, num_users,
                                              num_items, seed))
