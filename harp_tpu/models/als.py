"""Alternating least squares — explicit and implicit (confidence-weighted).

Reference parity: daal_als (ALSDaalCollectiveMapper.java:49 — implicit ALS on CSR
with DAAL's 4 distributed train steps; Harp allgather:336 + bcast of step2/step3
partial results:396-490) and daal_als_batch.

TPU-native: the factor matrices stay REPLICATED between half-iterations (they are
small: entities × rank); each half-iteration a worker solves the normal equations
for its shard of users (then items) as one batched Cholesky solve on the MXU, and
one all_gather re-replicates the updated factor — DAAL's step1-4 dance collapses
to "batched local solve + allgather". Ragged observed-item lists become padded
(entity, max_nnz) index/value buckets (SURVEY §7 sparse-data recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.collectives import lax_ops
from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    lam: float = 0.1            # L2 (DAAL: lambda)
    alpha: float = 40.0         # implicit confidence weight (DAAL: alpha)
    iterations: int = 10
    implicit: bool = True


def pad_csr_lists(rows, cols, vals, num_rows, num_workers):
    """(entity → padded neighbor list): idx (R_pad, M), val (R_pad, M), mask."""
    order = np.argsort(rows, kind="stable")
    r, c, v = rows[order], cols[order], vals[order]
    rpw = -(-num_rows // num_workers)
    r_pad = rpw * num_workers
    counts = np.bincount(r, minlength=r_pad)
    m = max(int(counts.max()), 1)
    idx = np.zeros((r_pad, m), np.int32)
    val = np.zeros((r_pad, m), np.float32)
    mask = np.zeros((r_pad, m), np.float32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(len(r)) - starts[r]          # slot within each row bucket
    idx[r, pos] = c
    val[r, pos] = v
    mask[r, pos] = 1.0
    return idx, val, mask


def _half_step(factor_other, idx, val, mask, cfg: ALSConfig,
               axis_name: str = WORKERS):
    """Solve this worker's block of one side's normal equations.

    factor_other: replicated (E_other, K). idx/val/mask: this worker's padded
    lists (E_local, M). Returns the updated local block (E_local, K).
    """
    k = cfg.rank
    vi = factor_other[idx]                      # (E_local, M, K)
    vi = vi * mask[..., None]
    if cfg.implicit:
        # Hu, Koren, Volinsky: A = V'V + V'(C−I)V + λI;  b = V'C·p (p=1 observed)
        conf = cfg.alpha * val * mask          # c − 1
        gram = jax.lax.dot_general(             # V'V over ALL entities (replicated)
            factor_other, factor_other, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        a = gram[None] + jnp.einsum("emk,em,eml->ekl", vi, conf, vi)
        b = jnp.einsum("emk,em->ek", vi, (1.0 + conf) * mask)
    else:
        # explicit: normal equations over observed entries only
        a = jnp.einsum("emk,eml->ekl", vi, vi)
        b = jnp.einsum("emk,em->ek", vi, val * mask)
    a = a + cfg.lam * jnp.eye(k, dtype=a.dtype)[None]
    return jax.scipy.linalg.solve(a, b[..., None], assume_a="pos")[..., 0]


def _train(u_idx, u_val, u_mask, i_idx, i_val, i_mask, u0, v0, cfg: ALSConfig,
           axis_name: str = WORKERS):
    def iteration(carry, _):
        u, v = carry                             # both replicated (E, K)
        # users half-step: local block solve, then re-replicate
        u_block = _half_step(v, u_idx, u_val, u_mask, cfg, axis_name)
        u = lax_ops.allgather(u_block, axis_name)
        v_block = _half_step(u, i_idx, i_val, i_mask, cfg, axis_name)
        v = lax_ops.allgather(v_block, axis_name)
        # monitor: explicit squared error on observed entries of the user shard
        pred = jnp.einsum("emk,ek->em", v[u_idx] * u_mask[..., None], u_block)
        tgt = u_val if not cfg.implicit else (u_mask * 1.0)
        sse = jax.lax.psum(jnp.sum(u_mask * (tgt - pred) ** 2), axis_name)
        cnt = jax.lax.psum(jnp.sum(u_mask), axis_name)
        return (u, v), jnp.sqrt(sse / jnp.maximum(cnt, 1.0))

    (u, v), rmse = jax.lax.scan(iteration, (u0, v0), None,
                                length=cfg.iterations)
    return u, v, rmse


class ALS:
    """Distributed ALS over a HarpSession mesh (daal_als parity)."""

    def __init__(self, session: HarpSession, config: ALSConfig):
        self.session = session
        self.config = config
        self._fns = {}

    def fit(self, rows, cols, vals, num_users: int, num_items: int,
            seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (U (num_users, K), V (num_items, K), rmse-per-iteration)."""
        sess, cfg = self.session, self.config
        w = sess.num_workers
        u_idx, u_val, u_mask = pad_csr_lists(rows, cols, vals, num_users, w)
        i_idx, i_val, i_mask = pad_csr_lists(cols, rows, vals, num_items, w)
        rng = np.random.default_rng(seed)
        scale = 1.0 / np.sqrt(cfg.rank)
        u0 = (scale * rng.random((u_idx.shape[0], cfg.rank))).astype(np.float32)
        v0 = (scale * rng.random((i_idx.shape[0], cfg.rank))).astype(np.float32)
        # zero phantom padding rows: the implicit-mode gram V'V sums over ALL
        # rows of the replicated factor, so random init there would bias the
        # first half-iteration's normal equations
        u0[num_users:] = 0.0
        v0[num_items:] = 0.0

        key = (u_idx.shape, i_idx.shape)
        if key not in self._fns:
            self._fns[key] = sess.spmd(
                lambda a, b, c, d, e, f, g, h: _train(a, b, c, d, e, f, g, h, cfg),
                in_specs=(sess.shard(),) * 6 + (sess.replicate(),) * 2,
                out_specs=(sess.replicate(),) * 3)
        u, v, rmse = self._fns[key](
            sess.scatter(u_idx), sess.scatter(u_val), sess.scatter(u_mask),
            sess.scatter(i_idx), sess.scatter(i_val), sess.scatter(i_mask),
            sess.replicate_put(u0), sess.replicate_put(v0))
        return (np.asarray(u)[:num_users], np.asarray(v)[:num_items],
                np.asarray(rmse))
