"""K-means with simulated trusted-enclave (SGX/TEE) overheads — sgxsimu.

Reference parity: experimental/kmeans/sgxsimu (KMeansCollectiveMapper.java:50,
Constants.java) — the reference's privacy-preserving-computation COST MODEL
study: run normal K-means and inject modeled Intel-SGX enclave overheads
(Thread.sleep of analytically computed ms, simuOverhead:530) so the wall
clock shows what the workload would cost inside enclaves. The model, with
the reference's published microbenchmark constants (×1000 cycles at the
stated clock, Constants.java:29-40):

* enclave creation  — per thread: ``creation_enclave_fix +
  enclave_total_kb * creation_enclave_kb`` kcycles
  (KMeansCollectiveMapper.java:177)
* local attestation — ``C(threads, 2) + (workers-1) * threads`` pairings
  (KMeansCollectiveMapper.java:192)
* compute Ecall/Ocall per task per iteration — ``Ecall|Ocall +
  kb(data) * cross_enclave_per_kb`` kcycles: points chunk into the thread
  enclave (CenCalcTask.java:130-132), centroid table in/out of the merge
  enclave (CenCalcTask.java:69-82, CenMergeTask.java:55-70)
* page swap — ``swap_page_penalty`` per 4 KB page by which the per-thread
  working set exceeds the effective enclave; the reference defines the
  constant but ships the term commented out (CenCalcTask.java:134-136), so
  it is opt-in here (``include_page_swap``)
* comm per collective per iteration — ``Ocall + Ecall*(workers-1)`` plus
  ``kb(table) * cross_enclave_per_kb`` kcycles for regroup and allgather
  (KMeansCollectiveMapper.java:300-343)

TPU-native reformulation: the reference slept inside its compute threads;
sleeping inside a jitted SPMD program is impossible (and would poison every
measurement), so the model here is ANALYTICAL-FIRST — run the real fit,
measure the clean per-iteration time, then report modeled buckets and the
modeled slowdown. ``simulate=True`` additionally sleeps the modeled per-
iteration cost between compiled iteration chunks (the reference's
Thread.sleep shape) so the wall clock demonstrates the slowdown. The
"enclave" maps to a per-worker protected memory budget on the host side of
a confidential-computing deployment; the cycle constants stay configurable
for other TEEs.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SGXCostConstants:
    """Reference Constants.java:29-40 — kilocycle costs on a 3.4 GHz
    SGX-enabled CPU (ms_per_kcycle converts to milliseconds)."""

    ecall: float = 8.5                      # kcycles per ECALL
    ocall: float = 9.0                      # kcycles per OCALL
    cross_enclave_per_kb: float = 1.4       # kcycles per KB crossing
    creation_enclave_fix: float = 221000.0  # kcycles per enclave create
    creation_enclave_kb: float = 22.677     # kcycles per KB of enclave
    local_attestation: float = 80.0         # kcycles per pairing
    remote_attestation: float = 27200.0     # kcycles (unused by kmeans)
    swap_page_penalty: float = 40.0         # kcycles per swapped 4KB page
    ms_per_kcycle: float = 0.0002941        # 3.4 GHz

    def ms(self, kcycles: float) -> float:
        return kcycles * self.ms_per_kcycle


@dataclasses.dataclass(frozen=True)
class SGXSimuConfig:
    """Reference launcher knobs (Constants.java ENCLAVE_* config keys)."""

    enclave_total_mb: int = 96      # total enclave capacity per thread
    enclave_per_thd_mb: int = 96    # effective enclave per thread
    threads_per_worker: int = 1     # reference numThreads (one enclave each)
    include_page_swap: bool = False  # the commented-out reference term
    constants: SGXCostConstants = dataclasses.field(
        default_factory=SGXCostConstants)


def _kb_of_doubles(count: int) -> float:
    """dataDoubleSizeKB (CenCalcTask.java): doubles are 8 bytes."""
    return count * 8.0 / 1024.0


def model_kmeans_overheads(n_points: int, dim: int, k: int, workers: int,
                           iterations: int, cfg: SGXSimuConfig) -> dict:
    """Modeled overhead buckets in ms — the reference's five LOG.info totals
    (KMeansCollectiveMapper.java:368-372).

    All ``*_ms`` buckets are PER WORKER: in the reference each mapper sleeps
    its own overhead concurrently, so the wall-clock penalty of the gang is
    one worker's share, not the sum. ``gang_total_overhead_ms`` carries the
    serial sum (worker-seconds of overhead) for energy/cost accounting."""
    c = cfg.constants
    thr = cfg.threads_per_worker
    # ---- init: enclave creation + local attestation (run once) ---------- #
    creation_ms = thr * c.ms(
        c.creation_enclave_fix + cfg.enclave_total_mb * 1024
        * c.creation_enclave_kb)
    pairings = math.comb(thr, 2) + (workers - 1) * thr
    attestation_ms = c.ms(pairings * c.local_attestation)
    # ---- per-iteration compute: Ecall/Ocall + crossing costs ------------ #
    pts_per_task = n_points / (workers * thr)
    pts_kb = _kb_of_doubles(int(pts_per_task * dim))
    cen_kb = _kb_of_doubles(k * (dim + 1))        # reference cenVecSize=dim+1
    # points chunk into each task enclave (CenCalcTask.java:130-132); the
    # thr tasks of one worker run serially w.r.t. the enclave boundary (the
    # reference's simuOverhead sleeps on the task thread inside submit/join)
    calc_ecall = thr * c.ms(c.ecall + pts_kb * c.cross_enclave_per_kb)
    # centroid table out of each calc enclave + in/out of the merge enclave
    # (CenCalcTask.java:69-82: one Ecall + one Ocall on the table;
    # CenMergeTask.java:55-70: one Ecall per merged partition set)
    calc_ocall = thr * c.ms(c.ocall + cen_kb * c.cross_enclave_per_kb)
    merge_ecall = thr * c.ms(c.ecall + cen_kb * c.cross_enclave_per_kb)
    comp_ms = calc_ecall + calc_ocall + merge_ecall
    # page swap: working set beyond the effective enclave, 4KB pages
    swap_ms = 0.0
    if cfg.include_page_swap:
        work_kb = pts_kb + cen_kb
        excess_kb = max(0.0, work_kb - cfg.enclave_per_thd_mb * 1024)
        swap_ms = thr * c.ms(c.swap_page_penalty * (excess_kb / 4.0))
    # ---- per-iteration comm: regroup + allgather cross-enclave ---------- #
    # (KMeansCollectiveMapper.java:300-343: Ocall + Ecall*(W-1) + table KB)
    per_coll = (c.ms(c.ocall + c.ecall * (workers - 1))
                + c.ms(cen_kb * c.cross_enclave_per_kb))
    comm_ms = 2 * per_coll                        # regroup + allgather
    per_iter = comp_ms + swap_ms + comm_ms
    return {
        "init_ms": creation_ms + attestation_ms,
        "comp_ecall_ms_per_iter": calc_ecall + merge_ecall,
        "comp_ocall_ms_per_iter": calc_ocall,
        "comp_swap_ms_per_iter": swap_ms,
        "comm_ms_per_iter": comm_ms,
        "overhead_ms_per_iter": per_iter,
        "total_overhead_ms": (creation_ms + attestation_ms
                              + per_iter * iterations),
        "gang_total_overhead_ms": workers * (
            creation_ms + attestation_ms + per_iter * iterations),
    }


class SGXSimuKMeans:
    """Run the real distributed K-means and report (optionally emulate) the
    modeled enclave overheads — experimental/kmeans/sgxsimu parity."""

    def __init__(self, session, kmeans_config, simu: Optional[SGXSimuConfig]
                 = None):
        from harp_tpu.models.kmeans import KMeans

        self.session = session
        self.kmeans = KMeans(session, kmeans_config)
        self.config = kmeans_config
        self.simu = simu or SGXSimuConfig()

    def fit(self, points: np.ndarray, centroids0: np.ndarray,
            simulate: bool = False):
        """Returns (centroids, costs, report). ``simulate=True`` sleeps the
        modeled per-iteration overhead between compiled iteration chunks so
        the wall clock shows the enclave-cost shape (the reference's
        simuOverhead Thread.sleep); the numeric result is identical either
        way — the model never perturbs the math."""
        sess, cfg = self.session, self.config
        n, d = points.shape
        model = model_kmeans_overheads(
            n, d, cfg.num_centroids, sess.num_workers, cfg.iterations,
            self.simu)
        pts_dev, cen_dev = self.kmeans.prepare(points, centroids0)
        self.kmeans.fit_prepared(pts_dev, cen_dev)        # compile + warm
        t0 = time.perf_counter()
        cen, costs = self.kmeans.fit_prepared(pts_dev, cen_dev)
        cen = np.asarray(cen)
        costs = np.asarray(costs)
        clean_s = time.perf_counter() - t0
        report = dict(model)
        if simulate:
            # emulate the enclave-cost SHAPE: one compiled chunk per
            # iteration with the modeled per-iteration overhead slept
            # between chunks (each worker sleeps only its OWN share — the
            # reference's concurrent per-mapper simuOverhead). Lloyd
            # chunking is bitwise-identical to the full scan
            # (kmeans.fit_checkpointed docstring), so the numeric result is
            # unchanged.
            from harp_tpu.models.kmeans import KMeans

            one_iter = KMeans(
                sess, dataclasses.replace(cfg, iterations=1))._fit
            time.sleep(model["init_ms"] / 1e3)
            cen_d, sim_costs = cen_dev, []
            t1 = time.perf_counter()
            for _ in range(cfg.iterations):
                cen_d, cost = one_iter(pts_dev, cen_d)
                sim_costs.extend(np.asarray(cost).tolist())
                time.sleep(model["overhead_ms_per_iter"] / 1e3)
            sim_s = time.perf_counter() - t1
            cen = np.asarray(cen_d)
            costs = np.asarray(sim_costs, costs.dtype)
            report["simulated_ms_per_iter"] = (
                sim_s * 1e3 / max(cfg.iterations, 1))
        clean_ms_per_iter = clean_s * 1e3 / max(cfg.iterations, 1)
        report["clean_ms_per_iter"] = clean_ms_per_iter
        report["modeled_slowdown"] = (
            (clean_ms_per_iter + model["overhead_ms_per_iter"])
            / clean_ms_per_iter if clean_ms_per_iter > 0 else float("inf"))
        return cen, costs, report
