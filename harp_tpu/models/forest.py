"""Decision trees and random forests — level-order histogram building.

Reference parity: daal_dtree, daal_dforest (SURVEY §2.7) and contrib rf /
randomforest / com.rf.fast (three random-forest variants).

TPU-native: features are quantile-binned host-side (uint8 bins); a tree trains
level-order — for every tree level one fused histogram pass accumulates
(node, feature, bin, class) weighted counts via ``segment_sum`` (psum'd across
workers), Gini gains for ALL candidate splits evaluate as one vectorized cumsum
expression, and sample→node assignments advance with a gather. A forest is
``vmap`` over trees: per-tree Poisson bootstrap weights + random feature masks
give the usual decorrelation, and XLA batches the whole ensemble's histogram
passes onto the MXU together.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class TreeConfig:
    depth: int = 4              # number of split levels
    num_bins: int = 16
    num_classes: int = 2
    num_trees: int = 1          # >1 → random forest
    feature_fraction: float = 1.0


def bin_features(x: np.ndarray, num_bins: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Quantile-bin each feature; returns (bins (N, D) int32, edges (D, B-1))."""
    qs = np.linspace(0.0, 1.0, num_bins + 1)[1:-1]
    edges = np.quantile(x, qs, axis=0).T.astype(np.float32)    # (D, B-1)
    bins = np.stack([np.searchsorted(edges[d], x[:, d], side="right")
                     for d in range(x.shape[1])], axis=1)
    return bins.astype(np.int32), edges


def _train_tree(bins, y, weight, feat_mask, cfg: TreeConfig,
                axis_name: str = WORKERS):
    """One tree. bins (N_local, D) int32; y (N_local,) int32; weight (N_local,)
    bootstrap weights; feat_mask (D,) 1=usable.

    Returns (feature (T,), split_bin (T,), leaf_class (L,)) where T = number of
    internal nodes (2^depth − 1) and L = 2^depth leaves, level-order indexed.
    """
    n_local, d = bins.shape
    b, c = cfg.num_bins, cfg.num_classes
    y_oh = jax.nn.one_hot(y, c, dtype=jnp.float32) * weight[:, None]

    def level_pass(a, num_nodes):
        """Histogram for the current level: (num_nodes, D, B, C)."""
        idx = (a[:, None] * (d * b) + jnp.arange(d)[None, :] * b + bins)
        flat = jax.ops.segment_sum(
            jnp.broadcast_to(y_oh[:, None, :], (n_local, d, c)).reshape(-1, c),
            idx.reshape(-1), num_segments=num_nodes * d * b)
        hist = flat.reshape(num_nodes, d, b, c)
        return jax.lax.psum(hist, axis_name)

    features, split_bins = [], []
    a = jnp.zeros((n_local,), jnp.int32)     # index within current level
    for level in range(cfg.depth):
        num_nodes = 2 ** level
        hist = level_pass(a, num_nodes)
        left = jnp.cumsum(hist, axis=2)                  # counts with bin <= t
        total = left[:, :, -1:, :]
        right = total - left
        ln = left.sum(-1)                                # (nodes, D, B)
        rn = right.sum(-1)
        gini_l = 1.0 - jnp.sum(jnp.square(left), -1) / jnp.maximum(ln * ln, 1e-12)
        gini_r = 1.0 - jnp.sum(jnp.square(right), -1) / jnp.maximum(rn * rn, 1e-12)
        tot_n = jnp.maximum(ln + rn, 1e-12)
        score = (ln * gini_l + rn * gini_r) / tot_n
        # forbid empty splits, the last bin (nothing right), masked features
        bad = (ln < 1e-6) | (rn < 1e-6)
        score = jnp.where(bad, jnp.inf, score)
        score = jnp.where(feat_mask[None, :, None] > 0, score, jnp.inf)
        flat = jnp.argmin(score.reshape(num_nodes, -1), axis=1)
        feat = (flat // b).astype(jnp.int32)             # (nodes,)
        sbin = (flat % b).astype(jnp.int32)
        features.append(feat)
        split_bins.append(sbin)
        # advance assignments: right if bin > split_bin of the sample's node
        my_feat = feat[a]
        my_bin = sbin[a]
        sample_bin = jnp.take_along_axis(bins, my_feat[:, None], axis=1)[:, 0]
        go_right = (sample_bin > my_bin).astype(jnp.int32)
        a = a * 2 + go_right

    # leaves: class histogram at the final level
    num_leaves = 2 ** cfg.depth
    leaf_hist = jax.lax.psum(
        jax.ops.segment_sum(y_oh, a, num_segments=num_leaves), axis_name)
    leaf_class = jnp.argmax(leaf_hist, axis=1).astype(jnp.int32)
    return (jnp.concatenate(features), jnp.concatenate(split_bins), leaf_class)


def _train_forest(bins, y, keys, cfg: TreeConfig, axis_name: str = WORKERS):
    d = bins.shape[1]

    def one_tree(key):
        kw, kf = jax.random.split(key)
        weight = jax.random.poisson(kw, 1.0, (bins.shape[0],)).astype(jnp.float32)
        if cfg.feature_fraction < 1.0:
            keep = jax.random.uniform(kf, (d,)) < cfg.feature_fraction
            # never mask every feature
            keep = keep.at[jax.random.randint(kf, (), 0, d)].set(True)
            mask = keep.astype(jnp.float32)
        else:
            mask = jnp.ones((d,), jnp.float32)
        return _train_tree(bins, y, weight, mask, cfg, axis_name)

    return jax.vmap(one_tree)(keys)


class DecisionTree:
    """daal_dtree parity: single Gini tree on binned features."""

    def __init__(self, session: HarpSession, config: TreeConfig):
        self.session = session
        self.config = config
        self._fns = {}
        self.edges = None
        self.tree = None

    def fit(self, x: np.ndarray, y: np.ndarray) -> "DecisionTree":
        sess, cfg = self.session, self.config
        bins, self.edges = bin_features(x, cfg.num_bins)
        key = bins.shape[1]
        if key not in self._fns:
            self._fns[key] = sess.spmd(
                lambda a, t: _train_tree(
                    a, t, jnp.ones((a.shape[0],), jnp.float32),
                    jnp.ones((a.shape[1],), jnp.float32), cfg),
                in_specs=(sess.shard(), sess.shard()),
                out_specs=(sess.replicate(),) * 3)
        out = self._fns[key](sess.scatter(jnp.asarray(bins)),
                             sess.scatter(jnp.asarray(y, jnp.int32)))
        self.tree = jax.tree.map(np.asarray, out)
        return self

    def _predict_tree(self, tree, bins: np.ndarray) -> np.ndarray:
        feats, sbins, leaf_class = tree
        cfg = self.config
        a = np.zeros(bins.shape[0], np.int64)
        off = 0
        for level in range(cfg.depth):
            idx = off + a
            f, sb = feats[idx], sbins[idx]
            go_right = bins[np.arange(bins.shape[0]), f] > sb
            a = a * 2 + go_right
            off += 2 ** level
        return leaf_class[a]

    def predict(self, x: np.ndarray) -> np.ndarray:
        bins = np.stack([np.searchsorted(self.edges[d], x[:, d], side="right")
                         for d in range(x.shape[1])], axis=1)
        return self._predict_tree(self.tree, bins).astype(np.int32)


class RandomForest(DecisionTree):
    """daal_dforest / contrib rf parity: bootstrap + feature-masked trees."""

    def fit(self, x: np.ndarray, y: np.ndarray, seed: int = 0) -> "RandomForest":
        sess, cfg = self.session, self.config
        bins, self.edges = bin_features(x, cfg.num_bins)
        keys = jax.random.split(jax.random.PRNGKey(seed), cfg.num_trees)
        key = (bins.shape[1], cfg.num_trees)
        if key not in self._fns:
            self._fns[key] = sess.spmd(
                lambda a, t, ks: _train_forest(a, t, ks, cfg),
                in_specs=(sess.shard(), sess.shard(), sess.replicate()),
                out_specs=(sess.replicate(),) * 3)
        out = self._fns[key](sess.scatter(jnp.asarray(bins)),
                             sess.scatter(jnp.asarray(y, jnp.int32)), keys)
        self.tree = jax.tree.map(np.asarray, out)
        return self

    def predict(self, x: np.ndarray) -> np.ndarray:
        bins = np.stack([np.searchsorted(self.edges[d], x[:, d], side="right")
                         for d in range(x.shape[1])], axis=1)
        feats, sbins, leaf_class = self.tree
        votes = np.zeros((x.shape[0], self.config.num_classes), np.int64)
        for t in range(self.config.num_trees):
            pred = self._predict_tree((feats[t], sbins[t], leaf_class[t]), bins)
            votes[np.arange(x.shape[0]), pred] += 1
        return votes.argmax(1).astype(np.int32)
