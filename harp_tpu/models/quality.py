"""Quality metrics — distributed classification/regression metric sets.

Reference parity: daal_quality_metrics (SURVEY §2.7 — DAAL's quality-metric sets
for binary/multiclass confusion matrices wrapped in a Harp job).

TPU-native: the confusion matrix is a one-hot matmul psum'd across workers; all
derived metrics (accuracy, precision/recall/F1 per class, specificity, AUC by
rank statistic, regression RMSE/MAE/R²) are computed replicated from it.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.session import HarpSession


def confusion_matrix(y_true, y_pred, num_classes: int,
                     axis_name: str = WORKERS) -> jax.Array:
    """(C, C) matrix: rows = true class, cols = predicted; psum'd (SPMD)."""
    t = jax.nn.one_hot(y_true, num_classes, dtype=jnp.float32)
    p = jax.nn.one_hot(y_pred, num_classes, dtype=jnp.float32)
    cm = jax.lax.dot_general(t, p, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return jax.lax.psum(cm, axis_name)


def classification_metrics(cm: jax.Array) -> Dict[str, jax.Array]:
    """Derive the DAAL multiclass metric set from a confusion matrix."""
    total = jnp.sum(cm)
    tp = jnp.diagonal(cm)
    fp = jnp.sum(cm, axis=0) - tp
    fn = jnp.sum(cm, axis=1) - tp
    tn = total - tp - fp - fn
    eps = 1e-12
    precision = tp / jnp.maximum(tp + fp, eps)
    recall = tp / jnp.maximum(tp + fn, eps)
    return {
        "accuracy": jnp.sum(tp) / jnp.maximum(total, eps),
        "precision": precision,
        "recall": recall,
        "f1": 2 * precision * recall / jnp.maximum(precision + recall, eps),
        "specificity": tn / jnp.maximum(tn + fp, eps),
    }


def binary_auc(y_true, scores, axis_name: str = WORKERS) -> jax.Array:
    """ROC-AUC via the Mann-Whitney rank statistic, computed replicated after an
    all_gather of (score, label) pairs (SPMD)."""
    s = jax.lax.all_gather(scores, axis_name, tiled=True)
    t = jax.lax.all_gather(y_true, axis_name, tiled=True).astype(jnp.float32)
    # tie-averaged ranks: rank(v) = (#{s < v} + #{s <= v} + 1) / 2
    s_sorted = jnp.sort(s)
    lo = jnp.searchsorted(s_sorted, s, side="left").astype(jnp.float32)
    hi = jnp.searchsorted(s_sorted, s, side="right").astype(jnp.float32)
    ranks = (lo + hi + 1.0) / 2.0
    n_pos = jnp.sum(t)
    n_neg = t.shape[0] - n_pos
    rank_sum = jnp.sum(ranks * t)
    return (rank_sum - n_pos * (n_pos + 1) / 2) / jnp.maximum(n_pos * n_neg,
                                                              1e-12)


def regression_metrics(y_true, y_pred, axis_name: str = WORKERS
                       ) -> Dict[str, jax.Array]:
    """psum'd RMSE / MAE / R² (SPMD)."""
    n = jax.lax.psum(jnp.asarray(y_true.shape[0], jnp.float32), axis_name)
    se = jax.lax.psum(jnp.sum((y_true - y_pred) ** 2), axis_name)
    ae = jax.lax.psum(jnp.sum(jnp.abs(y_true - y_pred)), axis_name)
    s = jax.lax.psum(jnp.sum(y_true), axis_name)
    ss = jax.lax.psum(jnp.sum(y_true * y_true), axis_name)
    var = ss - s * s / n
    return {
        "rmse": jnp.sqrt(se / n),
        "mae": ae / n,
        "r2": 1.0 - se / jnp.maximum(var, 1e-12),
    }


class QualityMetrics:
    """Session front-end (daal_quality_metrics parity)."""

    def __init__(self, session: HarpSession):
        self.session = session
        self._fns = {}

    def classification(self, y_true: np.ndarray, y_pred: np.ndarray,
                       num_classes: int) -> Dict[str, np.ndarray]:
        sess = self.session
        key = ("clf", num_classes)
        if key not in self._fns:
            def fn(t, p):
                cm = confusion_matrix(t, p, num_classes)
                out = classification_metrics(cm)
                out["confusion"] = cm
                return out
            self._fns[key] = sess.spmd(fn, in_specs=(sess.shard(),) * 2,
                                       out_specs=sess.replicate())
        out = self._fns[key](sess.scatter(jnp.asarray(y_true, jnp.int32)),
                             sess.scatter(jnp.asarray(y_pred, jnp.int32)))
        return {k: np.asarray(v) for k, v in out.items()}

    def auc(self, y_true: np.ndarray, scores: np.ndarray) -> float:
        sess = self.session
        if "auc" not in self._fns:
            self._fns["auc"] = sess.spmd(
                binary_auc, in_specs=(sess.shard(),) * 2,
                out_specs=sess.replicate())
        return float(self._fns["auc"](
            sess.scatter(jnp.asarray(y_true, jnp.int32)),
            sess.scatter(jnp.asarray(scores, jnp.float32))))

    def regression(self, y_true: np.ndarray, y_pred: np.ndarray
                   ) -> Dict[str, float]:
        sess = self.session
        if "reg" not in self._fns:
            self._fns["reg"] = sess.spmd(
                regression_metrics, in_specs=(sess.shard(),) * 2,
                out_specs=sess.replicate())
        out = self._fns["reg"](sess.scatter(jnp.asarray(y_true, jnp.float32)),
                               sess.scatter(jnp.asarray(y_pred, jnp.float32)))
        return {k: float(v) for k, v in out.items()}
