"""Algorithm library — the ml/java + ml/daal + contrib application inventory,
re-built TPU-native. Import the submodule you need; nothing heavy is imported
eagerly (each model compiles its own SPMD program on first use).

Families (reference dirs → modules):
  kmeans (5 comm variants)          → models.kmeans
  sgd/ + experimental daal_sgd      → models.sgd_mf
  daal_cov/pca/mom/qr/svd/...       → models.stats
  daal_linreg/daal_ridgereg         → models.linear
  daal_naive                        → models.naive_bayes
  contrib/mlr                       → models.logistic
  daal_svm + contrib/svm            → models.svm
  daal_knn                          → models.knn
  daal_als (+ _batch)               → models.als
  ccd/ (CCD++ MF)                   → models.ccd
  lda/ (CGS) + contrib/lda (CVB0)   → models.lda
  daal_nn                           → models.nn
  daal_optimization_solvers         → models.solvers
  contrib/simplepagerank            → models.pagerank
  wdamds/ (WDA-SMACOF MDS)          → models.mds
  daal_em (GMM)                     → models.em
  daal_quality_metrics              → models.quality
  daal_{stump,adaboost,logitboost,
        brownboost}                 → models.boosting
  daal_dtree/daal_dforest + rf      → models.forest
  daal_ar (association rules)       → models.assoc
  sahad/ + subgraph/ (color coding) → models.subgraph
"""
