"""LDA via collapsed Gibbs sampling with model rotation.

Reference parity: ml/java lda (LDAMPCollectiveMapper.java:51 — SparseLDA CGS with
the word-topic table ring-rotating via Rotator:257 and doc-topic tables local;
likelihood via allreduce:731 — BASELINE's "harp-java CGS-LDA, dynamic scheduler +
asynchronous rotation") and contrib/lda (CVB0).

TPU-native reformulation (SURVEY §7 "hard parts" — async semantics under SPMD):

* Docs are sharded over workers; the word-topic count matrix is split into W
  vocab blocks that ring-rotate (``ppermute``) — Harp's Rotator schedule. Words
  are dealt to blocks by **balanced (serpentine-LPT) corpus frequency** so a
  Zipf head word cannot blow up the per-(doc, block) token padding (the
  reference's clueweb vocabulary is exactly Zipf; set ``balance=False`` for the
  round-1 contiguous id ranges).
* Strictly sequential per-token Gibbs is hostile to SPMD, so sampling is
  **blocked**: during a hop, every token of the resident vocab block draws its
  topic from the CURRENT counts in parallel; count deltas are applied after the
  block (one-hot matmuls on the MXU). This is the standard blocked/stale-count
  approximation used by every distributed CGS (including Harp itself across
  workers — its staleness is per-rotation too, LDAMPCollectiveMapper rotates
  between updates); convergence is statistical, not token-sequential.
* Topic totals n_k are refreshed by psum once per hop — bounded staleness,
  replacing Harp's asynchronously drifting totals.
* The count WRITE rides the one-hot-GEMM scatter engine (ops/lane_pack —
  the shared software answer to TPU's missing per-lane HBM scatter), and
  ``vocab_sub_block=128`` additionally buckets tokens per 128-wide vocab
  SUB-block so the scatter GEMM is 128 lanes wide regardless of vocab size
  (FLOPs ∝ 128·K per token instead of vpb·K — the r5 large-vocab crossover
  remover; costs per-(doc, sub-block) padding, see bucketize_tokens_subblock).
* The reference splits the word-topic table into numModelSlices=2 pipelined
  slices (LDAMPCollectiveMapper wTableMap[k]) so rotation overlaps sampling.
  Both schedules exist here: ``num_model_slices=1`` (single-slice
  rotate_scan; XLA's async collective scheduler overlaps the block ppermute
  with the next hop's leading compute) and ``num_model_slices=2``
  (half-width blocks on collectives.rotation.pipelined_rotation — while one
  half-slice is being sampled the other is in flight, the reference's exact
  schedule). ``ablate_rotation=True`` keeps the compute schedule but drops
  the ppermute — a timing-only ablation benchmark/lda_overlap.py uses to
  measure the rotation's share of hop time (results in PERF.md).

Likelihood monitor: the REFERENCE formula, exactly (CalcLikelihoodTask.run:56 +
the topic-sum completion in printLikelihood, LDAMPCollectiveMapper.java:731-748
— MALLET's word-topic model-likelihood part):

    LL = Σ_{w,k: n_wk>0} [lgamma(β + n_wk) − lgamma(β)]
         − Σ_k lgamma(Vβ + n_k) + K·lgamma(Vβ)

allreduced per epoch, so BASELINE's time-to-likelihood rows are directly
measurable. :func:`full_model_log_likelihood` additionally adds the doc-topic
term of the full MALLET formula (the reference omits it) for model comparison,
and :func:`sequential_cgs_reference` is the single-device token-sequential CGS
oracle the convergence-parity test measures against.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu import telemetry
from harp_tpu.collectives import lax_ops, quantize, rotation
from harp_tpu.ops import lane_pack
from harp_tpu.parallel.mesh import WORKERS, fetch
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class LDAConfig:
    """Reference CLI parity (numTopics, alpha, beta, numIterations)."""

    num_topics: int = 10
    vocab: int = 100
    alpha: float = 0.1
    beta: float = 0.01
    epochs: int = 20
    method: str = "cgs"         # "cgs" (ml/java lda) or "cvb0" (contrib/lda)
    balance: bool = True        # serpentine-LPT word→block assignment
    wt_access: str = "auto"     # auto | gemm_scatter | gemm | gather — how
    #   tokens read/write the word-topic block.
    #   * "gather": row-gather read + segment_sum write (the r≤4 default).
    #     The r5 stage budget showed the segment_sum is 82% of the hop
    #     (2.25 of 2.73 ms/epoch on the bench config — XLA scatter
    #     serializes at ~8.5 ns/row).
    #   * "gemm_scatter" (r5): row-gather read, but the count WRITE becomes
    #     chunked one-hot GEMMs on the MXU — oh (chunk, vpb) in bf16
    #     (0/1 exact) against delta (chunk, K) in bf16 (CGS deltas are
    #     ±1/0, exact) with f32 accumulation, so counts stay exact while
    #     the scatter rides the MXU at tens of TF/s instead of the scatter
    #     unit. CGS only (CVB0's soft deltas are not bf16-exact).
    #   * "gemm": BOTH sides as full-width f32 one-hot matmuls (legacy).
    #   "auto" picks gemm_scatter for cgs — UNLESS the vocab block is wider
    #   than wt_gemm_scatter_max_vpb (below) and the sub-block layout is
    #   off, in which case it falls back to gather (ADVICE r5: the one-hot
    #   GEMM write costs vpb·K FLOPs per token, so a vpb~1M block would
    #   regress far below the segment_sum path; the r6 auto had no guard).
    #   The one-hot-GEMM implementation itself lives in ops/lane_pack.py
    #   (the shared scatter engine; bitwise-equal to the r5 in-module copy).
    wt_gemm_scatter_max_vpb: int = 65536   # auto-mode crossover guard: the
    #   widest vocab block auto still routes to gemm_scatter. The measured
    #   r5 crossover config (V=8000, K=64 → vpb=8064, vpb·K ≈ 516k FLOPs/
    #   token) still wins ~1.9x over segment_sum; the FLOP cost scales
    #   linearly in vpb while the scatter-unit cost does not, so 8x past
    #   the measured-winning width is where auto stops gambling. Explicit
    #   wt_access="gemm_scatter" is never overridden, and the vocab_sub_block
    #   layout ignores the guard (its one-hot is 128 lanes wide regardless
    #   of vpb — that layout exists precisely for the wide-vocab regime).
    vocab_sub_block: int = 0    # 0 = off; else (r6) the vocab-SUB-block token
    #   layout: tokens are bucketized per (vocab block, sub-block of this
    #   width), so the scatter's one-hot GEMM is `vocab_sub_block` lanes wide
    #   (one batched GEMM over all sub-blocks) instead of vpb wide — FLOPs
    #   ∝ 128 instead of V/(W·slices), which is what pushes large-vocab
    #   configs (vpb·K ≈ 512k, the measured r5 crossover) back toward the
    #   540M tokens/s no-scatter floor. Cost: per-(doc, sub-block) token
    #   padding (tracked in last_layout_stats). 128 = the MXU lane width.
    #   Requires method='cgs' and wt_access auto/gemm_scatter.
    num_model_slices: int = 1   # 1 = plain rotate_scan; 2 = the reference's
    #   numModelSlices=2 double-buffered schedule (half-width vocab blocks on
    #   pipelined_rotation: sample one half-slice while the other rotates)
    ablate_rotation: bool = False  # timing ablation ONLY: keep the exact
    #   compute schedule but skip the ppermute (results are wrong — blocks
    #   never move); lets benchmark/lda_overlap.py price the rotation
    ablate_stage: str = ""      # timing ablation ONLY ("gather" | "scatter" |
    #   "sample" | "gather+scatter"): drop that stage of the per-group update
    #   (results are wrong) so benchmark/lda_stages.py can price each stage of
    #   the hop by difference — the per-stage budget VERDICT r4 asked for
    minibatches_per_hop: int = 4  # sequential doc-group sub-steps per hop:
    #   fully-parallel draws let every token of a word resample against the
    #   SAME stale word-topic row each round (a word's tokens can never
    #   coordinate on a topic), which parks the chain at a diffuse fixed
    #   point; refreshing counts between doc-groups restores near-sequential
    #   mixing (the analog of the reference's per-thread token batches under
    #   the dymoro timer, Scheduler.java:110-121)
    quant: Optional[str] = None  # None | "int8" | "bf16": quantize the
    #   per-hop topic-total allreduce's WIRE format with error feedback
    #   carried through the rotation + epoch scans (collectives/quantize.py).
    #   The per-hop (K,) delta psum is LDA's allreduce hot hop (W·epochs
    #   calls per fit); sampling probabilities then run on slightly-perturbed
    #   totals — convergence-equivalent, not bit-identical (the parity test
    #   uses the deterministic CVB0 method so the comparison is pure
    #   quantization error, not CGS chain divergence). The circulating
    #   word-topic block stays exact: its counts ARE the model — unless
    #   quant_wt opts it in too (below).
    quant_wt: bool = False      # r10 (requires quant): ALSO quantize the
    #   circulating word-topic BLOCK rotation payload — the (vpb, K) hop
    #   that is LDA's dominant wire volume (the topic-total allreduce quant
    #   above moves only K floats/hop). int8/bf16 per the quant codec, with
    #   the error-feedback residual threaded through the EPOCH carry
    #   (rotation.rotate_scan/pipelined_rotation ``ef_state``), so an epoch
    #   boundary never drops the pending encode error. Counts become
    #   fractional on the wire (EF keeps the time-average exact) — the
    #   parity test again uses CVB0 so the delta is pure wire error.
    fused_dma: bool = False     # r10: the wt-block rotation hops ride the
    #   fused ring-DMA engine (ops/ring_dma) instead of ppermute — on TPU
    #   the block moves HBM → remote HBM in-kernel with no staging copies;
    #   bitwise-identical schedule on every backend. A quantized wt wire
    #   (quant_wt) takes precedence over fusion (rotation.py module doc).
    reshard: str = "auto"       # r12: HOW a world-size-changing resume moves
    #   the chain state (token assignments z + word-topic counts wt) onto
    #   this session's blocking: "device" = collectives/reshard.py bounded
    #   all_to_all rounds on the mesh (z rows ride the token-key
    #   permutation, wt rows ride the (word_block, word_slot) maps — no
    #   host gather of a sharded leaf), "ring" = the ppermute schedule,
    #   "host" = the PR 8 numpy re-match/rebuild (parity oracle + 1-worker
    #   fallback), "auto" = device when the mesh has >1 worker.
    reshard_chunk_bytes: int = 0  # 0 = collectives.reshard default (1 MiB)


def bucketize_tokens(docs: np.ndarray, num_blocks: int, vpb: int,
                     word_block: Optional[np.ndarray] = None,
                     word_slot: Optional[np.ndarray] = None,
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-side layout: (D, L) tokens → (D, W, Lb) grouped by home vocab block.

    Each hop then processes exactly the resident block's tokens (padded to the
    max per-(doc, block) count Lb) instead of sampling every token every hop.
    The stored token ids are block-LOCAL slots. ``word_block``/``word_slot``
    are optional id maps (see sgd_mf.serpentine_assign); default contiguous.
    """
    d, l = docs.shape
    rows = np.arange(d)[:, None]
    if word_block is None:
        block = np.minimum(docs // vpb, num_blocks - 1)
        slot = docs - block * vpb
    else:
        block = word_block[docs]
        slot = word_slot[docs]
    counts = np.zeros((d, num_blocks), np.int64)
    np.add.at(counts, (rows, block), 1)
    lb = max(int(counts.max()), 1)
    # padding slots hold local id 0 (in-range); mask zeroes their effect
    docs_b = np.zeros((d, num_blocks, lb), docs.dtype)
    mask_b = np.zeros((d, num_blocks, lb), np.float32)
    order = np.argsort(block, axis=1, kind="stable")
    sorted_block = np.take_along_axis(block, order, axis=1)
    sorted_slot = np.take_along_axis(slot, order, axis=1)
    bucket_starts = np.concatenate(
        [np.zeros((d, 1), np.int64), np.cumsum(counts, axis=1)[:, :-1]], axis=1)
    pos = np.arange(l)[None, :] - bucket_starts[rows, sorted_block]
    docs_b[rows, sorted_block, pos] = sorted_slot
    mask_b[rows, sorted_block, pos] = 1.0
    return docs_b, mask_b, lb


def bucketize_tokens_subblock(docs: np.ndarray, num_blocks: int, vpb: int,
                              sub: int, word_block: np.ndarray,
                              word_slot: np.ndarray
                              ) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Vocab-SUB-block layout: bucket tokens per (vocab block, ``sub``-wide
    sub-block of block-local slots), padded to the max per-(doc, sub-block)
    count Lbs. Returns ``(docs_b (D, NB, NS*Lbs), mask_b, lb, lbs)`` with
    ``lb = NS*Lbs`` and NS = vpb // sub; stored ids stay FULL block-local
    slots (gather and sampling are layout-agnostic), but within a (doc,
    block) row the tokens are grouped by sub-block, so the scatter can
    reshape its deltas to (NS, ·, K) and run one batched ``sub``-lane-wide
    one-hot GEMM (ops/lane_pack.gemm_scatter) instead of a vpb-wide one."""
    if vpb % sub:
        raise ValueError(f"vpb {vpb} must be a multiple of sub {sub}")
    ns = vpb // sub
    sub_of, _ = lane_pack.sub_block_split(word_slot, sub)
    fine_block = (word_block * ns + sub_of).astype(word_block.dtype)
    docs_f, mask_f, lbs = bucketize_tokens(
        docs, num_blocks * ns, vpb, fine_block, word_slot)
    d = docs.shape[0]
    docs_b = docs_f.reshape(d, num_blocks, ns * lbs)
    mask_b = mask_f.reshape(d, num_blocks, ns * lbs)
    return docs_b, mask_b, ns * lbs, lbs


class LDA:
    """Distributed CGS-LDA over a HarpSession mesh."""

    def __init__(self, session: HarpSession, config: LDAConfig):
        if config.method not in ("cgs", "cvb0"):
            raise ValueError(f"method must be 'cgs' or 'cvb0', got "
                             f"{config.method!r}")
        if config.num_model_slices not in (1, 2):
            raise ValueError(f"num_model_slices must be 1 or 2, got "
                             f"{config.num_model_slices}")
        if config.ablate_stage not in ("", "gather", "scatter", "sample",
                                       "gather+scatter"):
            raise ValueError(
                f"ablate_stage must be ''|gather|scatter|sample|"
                f"gather+scatter, got {config.ablate_stage!r}")
        if config.ablate_stage == "sample" and config.method == "cvb0":
            raise ValueError(
                "ablate_stage='sample' only supports method='cgs' (the "
                "cheap-shift replacement needs integer topic assignments)")
        if config.wt_access == "gemm_scatter" and config.method != "cgs":
            raise ValueError(
                "wt_access='gemm_scatter' requires method='cgs' (CVB0's "
                "soft deltas are not bf16-exact)")
        if config.quant_wt and config.quant is None:
            raise ValueError(
                "quant_wt=True requires quant='int8'|'bf16' (it selects "
                "WHICH payloads ride the quantized wire, not the codec)")
        if config.vocab_sub_block:
            if config.vocab_sub_block < 1:
                raise ValueError(
                    f"vocab_sub_block must be positive, got "
                    f"{config.vocab_sub_block}")
            if config.method != "cgs" or config.wt_access not in (
                    "auto", "gemm_scatter"):
                raise ValueError(
                    "vocab_sub_block requires method='cgs' with "
                    "wt_access='auto'/'gemm_scatter' (the sub-block layout "
                    "exists to narrow the gemm_scatter one-hot)")
        self.session = session
        self.config = config
        self._fns = {}
        self.last_layout_stats: dict = {}

    def _effective_minibatches(self, d_local: int) -> int:
        """Largest divisor of docs-per-worker within the configured budget —
        the sub-step count the compiled program actually runs."""
        return max(g for g in range(1, min(self.config.minibatches_per_hop,
                                           d_local) + 1) if d_local % g == 0)

    def _build(self, w: int, v_pad: int, lb: int, d_local: int,
               lbs: int = 0):
        cfg = self.config
        k = cfg.num_topics
        ns = cfg.num_model_slices
        nb = w * ns                           # rotating vocab blocks in total
        vpb = v_pad // nb                     # vocab per block
        shift = 0 if cfg.ablate_rotation else 1
        comm = (quantize.CommConfig(quant=cfg.quant)
                if cfg.quant is not None else None)
        nmb = self._effective_minibatches(d_local)
        dg = d_local // nmb
        if cfg.wt_access not in ("auto", "gemm_scatter", "gemm", "gather"):
            raise ValueError(f"wt_access must be auto|gemm_scatter|gemm|"
                             f"gather, got {cfg.wt_access!r}")
        # legacy full f32 one-hot path: explicit, or auto for CVB0 on
        # narrow blocks (cvb0's soft deltas cannot take the bf16 route)
        onehot_bytes = dg * lb * vpb * 4
        use_gemm = (cfg.wt_access == "gemm"
                    or (cfg.wt_access == "auto" and cfg.method == "cvb0"
                        and vpb <= 8192
                        and onehot_bytes <= 256 * 1024 * 1024))
        # gemm_scatter: bf16 one-hot GEMM count writes (exact for CGS's
        # ±1/0 deltas — lane_pack's 'exact_pm1' policy) instead of the
        # segment_sum that is 82% of the hop. Chunked by the engine so the
        # transient one-hot stays ≤ ~64 MB (zero-delta pad rows contribute
        # nothing). Auto guards on the block width (ADVICE r5): past
        # wt_gemm_scatter_max_vpb the vpb·K one-hot FLOPs lose to the
        # segment_sum — fall back to gather — except under the sub-block
        # layout, whose one-hot width is vocab_sub_block, not vpb.
        use_gemm_scatter = (cfg.wt_access == "gemm_scatter"
                            or (cfg.wt_access == "auto"
                                and cfg.method == "cgs"
                                and (bool(cfg.vocab_sub_block)
                                     or vpb <= cfg.wt_gemm_scatter_max_vpb)))
        # vocab-sub-block layout: the scatter runs as ONE batched GEMM over
        # (NS, dg·Lbs, K) deltas against `sub`-lane-wide one-hots — FLOPs
        # ∝ sub (=128), not vpb. Tokens arrive grouped by sub-block
        # (bucketize_tokens_subblock), ids stay full block-local slots.
        sub_w = cfg.vocab_sub_block
        use_sub = bool(sub_w) and use_gemm_scatter
        if use_sub:
            if not lbs or lb % lbs or vpb % sub_w:
                raise ValueError(
                    f"sub-block build needs lb {lb} = NS*lbs ({lbs}) and "
                    f"sub {sub_w} | vpb {vpb} (prepare() sets these)")
            ns_sub = vpb // sub_w
            scatter_chunk = lane_pack.scatter_chunk(dg * lbs, sub_w,
                                                    batch=ns_sub)
        else:
            ns_sub = 1
            scatter_chunk = lane_pack.scatter_chunk(dg * lb, vpb)
        # record the resolved write path (the auto guard makes it
        # shape-dependent, so tests/benches read it instead of re-deriving)
        self.last_layout_stats["wt_path"] = (
            "gemm" if use_gemm
            else "gemm_scatter_subblock" if use_sub
            else "gemm_scatter" if use_gemm_scatter
            else "gather")

        def fit_fn(docs_b, mask_b, z0, wt_block0, seed):
            # docs_b/mask_b/z0: (D_local, NB, Lb) — tokens pre-bucketed by home
            # vocab block (host-side, bucketize_tokens; ids are block-local
            # slots), so each hop touches only the resident block's tokens
            # instead of sampling all tokens and discarding (w-1)/w of draws.
            soft = cfg.method == "cvb0"

            def group_update(wt_block, tt_local, key, wl_g, ms_g, zs_g, dt_g):
                """Resample one doc-group's resident-block tokens from the
                CURRENT counts: p(z=k) ∝ (n_dk−cur+α)(n_wk−cur+β)/(n_k−cur+Vβ)."""
                if soft:
                    cur = zs_g * ms_g[..., None]              # (dg, Lb, K)
                else:
                    cur = (jax.nn.one_hot(zs_g, k, dtype=jnp.float32)
                           * ms_g[..., None])
                nd = dt_g[:, None, :] - cur                   # exclude self
                no_gather = "gather" in cfg.ablate_stage
                no_scatter = "scatter" in cfg.ablate_stage
                oh = None

                def apply_scatter(wt_b, delta):
                    """The ONE count-write path (shared by the full run and
                    the sample ablation, whose stage budget by subtraction
                    needs the unablated stages identical)."""
                    if use_gemm:
                        return wt_b + jax.lax.dot_general(
                            oh, delta.reshape(-1, k),
                            (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                    if use_sub:
                        # tokens are grouped (dg, NS, Lbs); key the one-hot
                        # on the within-sub slot and scatter all sub-blocks
                        # in one batched `sub`-lane GEMM
                        _, sub_slot = lane_pack.sub_block_split(
                            wl_g.reshape(dg, ns_sub, lbs), sub_w)
                        ids_s = sub_slot.transpose(1, 0, 2).reshape(
                            ns_sub, dg * lbs)
                        d_s = delta.reshape(dg, ns_sub, lbs, k).transpose(
                            1, 0, 2, 3).reshape(ns_sub, dg * lbs, k)
                        upd = lane_pack.gemm_scatter(
                            ids_s, d_s, sub_w, chunk=scatter_chunk,
                            policy="exact_pm1")
                        return wt_b + upd.reshape(vpb, k)
                    if use_gemm_scatter:
                        return wt_b + lane_pack.gemm_scatter(
                            wl_g.reshape(-1), delta.reshape(-1, k), vpb,
                            chunk=scatter_chunk, policy="exact_pm1")
                    return wt_b + jax.ops.segment_sum(
                        delta.reshape(-1, k), wl_g.reshape(-1),
                        num_segments=vpb)
                if use_gemm and not (no_gather and no_scatter):
                    # the scatter GEMM needs the one-hot even when the
                    # gather is ablated (building it is part of either
                    # stage's cost in gemm mode)
                    oh = jax.nn.one_hot(wl_g.reshape(-1), vpb,
                                        dtype=jnp.float32)   # (dg*Lb, vpb)
                if no_gather:
                    nw = 1.0 - cur                # ablation: skip the wt read
                elif use_gemm:
                    nw = (oh @ wt_block).reshape(cur.shape) - cur
                else:
                    nw = wt_block[wl_g] - cur
                nk = tt_local[None, None, :] - cur
                if cfg.ablate_stage == "sample":
                    # ablation: keep gather+scatter live (consume nw, emit a
                    # nonzero delta) but skip the categorical build + draw
                    gate = (nw.sum(-1) > 1e30).astype(jnp.int32)
                    zs_cheap = (zs_g + 1 + gate) % k
                    new = (jax.nn.one_hot(zs_cheap, k, dtype=jnp.float32)
                           * ms_g[..., None])
                    delta = new - cur
                    if not no_scatter:
                        wt_block = apply_scatter(wt_block, delta)
                    d_k = delta.sum(axis=(0, 1))
                    return (wt_block, tt_local + d_k, d_k, key,
                            zs_cheap, dt_g + delta.sum(axis=1))
                # PRODUCT space, not log space: p ∝ (nd+α)(nw+β)/(nk+Vβ)
                # directly. The log form cost 3 transcendentals per (token,
                # topic) and jax.random.categorical's gumbel trick 2 more —
                # ~5K logs/token of pure VPU-transcendental work at K
                # topics; inverse-CDF sampling needs ZERO (measured r4:
                # 39 → 68M tokens/s on the bench config). All factors are
                # nonnegative (counts exclude self) and bounded by doc
                # length/corpus counts, so f32 products are safe — the
                # sequential oracle uses the identical form.
                p = (jnp.maximum(nd + cfg.alpha, 0.0)
                     * jnp.maximum(nw + cfg.beta, 0.0)
                     / jnp.maximum(nk + cfg.vocab * cfg.beta, 1e-10))
                if soft:
                    # CVB0 (contrib/lda CVB0 LdaMapCollective): deterministic
                    # mean-field update — soft assignment = normalized
                    # probabilities (softmax(log p) ≡ p/Σp, minus the logs)
                    zs_new = (p / jnp.maximum(p.sum(-1, keepdims=True),
                                              1e-30)) * ms_g[..., None]
                    new = zs_new
                else:
                    key, sub = jax.random.split(key)
                    cdf = jnp.cumsum(p, axis=-1)
                    u = jax.random.uniform(sub, p.shape[:-1] + (1,),
                                           jnp.float32) * cdf[..., -1:]
                    zs_new = jnp.clip(jnp.sum((cdf < u), axis=-1), 0, k - 1)
                    new = (jax.nn.one_hot(zs_new, k, dtype=jnp.float32)
                           * ms_g[..., None])
                delta = new - cur                             # (dg, Lb, K)
                if not no_scatter:               # ablation: skip the wt write
                    wt_block = apply_scatter(wt_block, delta)
                d_k = delta.sum(axis=(0, 1))
                return (wt_block, tt_local + d_k, d_k, key,
                        zs_new, dt_g + delta.sum(axis=1))

            def sample_resident(carry, wt_block, src):
                """Sample every token whose home block ``src`` is resident."""
                if comm is None:
                    doc_topic, z, topic_tot, key = carry
                else:
                    doc_topic, z, topic_tot, key, qres = carry
                w_local = jnp.take(docs_b, src, axis=1)       # (D, Lb) slots
                mask_s = jnp.take(mask_b, src, axis=1)
                z_s = jnp.take(z, src, axis=1)

                def grp(carry2, xs):
                    wt_b, tt_loc, hop_d, key = carry2
                    wl_g, ms_g, zs_g, dt_g = xs
                    wt_b, tt_loc, d_k, key, zs_new, dt_new = group_update(
                        wt_b, tt_loc, key, wl_g, ms_g, zs_g, dt_g)
                    return (wt_b, tt_loc, hop_d + d_k, key), (zs_new, dt_new)

                z_shape = ((nmb, dg, lb, k) if soft else (nmb, dg, lb))
                (wt_block, _, hop_delta, key), (zs_new, dt_new) = jax.lax.scan(
                    grp,
                    (wt_block, topic_tot, jnp.zeros(k), key),
                    (w_local.reshape(nmb, dg, lb),
                     mask_s.reshape(nmb, dg, lb),
                     z_s.reshape(z_shape),
                     doc_topic.reshape(nmb, dg, k)))
                doc_topic = dt_new.reshape(d_local, k)
                zs_new = zs_new.reshape(z_s.shape)
                if soft:
                    z = jnp.where((jnp.arange(nb) == src)[None, :, None, None],
                                  zs_new[:, None, :, :], z)
                else:
                    z = jnp.where((jnp.arange(nb) == src)[None, :, None],
                                  zs_new[:, None, :], z)
                # bounded-staleness topic totals: refresh by psum once per hop
                if comm is None:
                    topic_tot = topic_tot + jax.lax.psum(hop_delta,
                                                         lax_ops.WORKERS)
                    return (doc_topic, z, topic_tot, key), wt_block
                # quantized wire format for the hop allreduce; EF residual
                # rides the rotation (and epoch) carry
                delta_sum, qres = lax_ops.allreduce(hop_delta, comm=comm,
                                                    residual=qres)
                topic_tot = topic_tot + delta_sum
                return (doc_topic, z, topic_tot, key, qres), wt_block

            def hop_body(carry, wt_block, t):
                # single-slice schedule: at hop t the resident block's home
                # worker is (wid - t) — Harp's plain Rotator ring
                src = (lax_ops.worker_id() - t) % w
                return sample_resident(carry, wt_block, src)

            def micro_body(carry, wt_half, t):
                # numModelSlices=2 schedule (LDAMPCollectiveMapper wTableMap):
                # even micro-steps sample an a-half-block (ids [0, w)), odd
                # ones a b-half-block (ids [w, 2w)); each advances around the
                # ring every SECOND micro-step, so while this half is being
                # sampled the other is in flight (pipelined_rotation)
                src = (t % 2) * w + (lax_ops.worker_id() - t // 2) % w
                return sample_resident(carry, wt_half, src)

            key = jax.random.fold_in(jax.random.PRNGKey(0),
                                     seed + lax_ops.worker_id())
            if cfg.method == "cvb0":
                doc_topic = (z0 * mask_b[..., None]).sum(axis=(1, 2))
            else:
                doc_topic = (jax.nn.one_hot(z0, k, dtype=jnp.float32)
                             * mask_b[..., None]).sum(axis=(1, 2))
            topic_tot = jax.lax.psum(doc_topic.sum(axis=0), lax_ops.WORKERS)

            lgamma = jax.scipy.special.gammaln
            v_beta = cfg.vocab * cfg.beta

            def ref_ll(wt, topic_tot):
                # REFERENCE log-likelihood (CalcLikelihoodTask.run:56 +
                # printLikelihood:731-748): nonzero word-topic cells only,
                # then the topic-sum completion terms. Exact for CGS (integer
                # counts); under CVB0 counts are fractional soft mass, so the
                # >0.5 cell test makes this an approximate monitor there
                nz = wt > 0.5
                ll_w = jax.lax.psum(
                    jnp.sum(jnp.where(nz, lgamma(wt + cfg.beta)
                                      - lgamma(cfg.beta), 0.0)),
                    lax_ops.WORKERS)
                return (ll_w - jnp.sum(lgamma(topic_tot + v_beta))
                        + k * lgamma(v_beta))

            # quant_wt: the wt-block hop rides the quantized wire; its EF
            # residual lives in the EPOCH carry (ef_state threading) so the
            # pending encode error survives epoch boundaries
            quant_wt = comm is not None and cfg.quant_wt
            wt_comm = comm if quant_wt else None

            def epoch(state, _):
                if quant_wt:
                    *core, wt_res = state
                    state = tuple(core)
                if comm is None:
                    doc_topic, z, topic_tot, wt, key = state
                    hop_carry = (doc_topic, z, topic_tot, key)
                else:
                    doc_topic, z, topic_tot, wt, key, qres = state
                    hop_carry = (doc_topic, z, topic_tot, key, qres)
                if ns == 1:
                    if quant_wt:
                        hop_carry, wt, wt_res = rotation.rotate_scan(
                            hop_body, hop_carry, wt, w, shift=shift,
                            comm=wt_comm, ef_state=wt_res,
                            fused_dma=cfg.fused_dma)
                    else:
                        hop_carry, wt = rotation.rotate_scan(
                            hop_body, hop_carry, wt, w, shift=shift,
                            fused_dma=cfg.fused_dma)
                else:
                    # local (2*vpb, K) block = [a-half; b-half]; 2w micro-steps
                    # bring both halves home again
                    if quant_wt:
                        hop_carry, sa, sb, wt_res = rotation.pipelined_rotation(
                            micro_body, hop_carry, wt[:vpb], wt[vpb:], 2 * w,
                            shift=shift, comm=wt_comm, ef_state=wt_res,
                            fused_dma=cfg.fused_dma)
                    else:
                        hop_carry, sa, sb = rotation.pipelined_rotation(
                            micro_body, hop_carry, wt[:vpb], wt[vpb:], 2 * w,
                            shift=shift, fused_dma=cfg.fused_dma)
                    wt = jnp.concatenate([sa, sb], axis=0)
                if comm is None:
                    doc_topic, z, topic_tot, key = hop_carry
                    out = (doc_topic, z, topic_tot, wt, key)
                else:
                    doc_topic, z, topic_tot, key, qres = hop_carry
                    out = (doc_topic, z, topic_tot, wt, key, qres)
                if quant_wt:
                    out = out + (wt_res,)
                ll = ref_ll(wt, topic_tot)
                return out, ll

            state0 = ((doc_topic, z0, topic_tot, wt_block0, key)
                      if comm is None else
                      (doc_topic, z0, topic_tot, wt_block0, key,
                       jnp.zeros((k,), jnp.float32)))
            if quant_wt:
                wt_res0 = (rotation.ef_zero(wt_block0) if ns == 1 else
                           (rotation.ef_zero(wt_block0[:vpb]),
                            rotation.ef_zero(wt_block0[vpb:])))
                state0 = state0 + (wt_res0,)
            state, ll = jax.lax.scan(epoch, state0, None, length=cfg.epochs)
            doc_topic, z, _, wt = state[:4]
            return doc_topic, wt, z, ll

        sess = self.session
        return sess.spmd(
            fit_fn,
            in_specs=(sess.shard(), sess.shard(), sess.shard(), sess.shard(),
                      sess.replicate()),
            out_specs=(sess.shard(), sess.shard(), sess.shard(),
                       sess.replicate()),
        )

    def prepare(self, docs: np.ndarray, seed: int = 0):
        """Bucketize + place tokens and initial counts on the mesh ONCE.

        Returns an opaque state for :meth:`fit_prepared` — keeps host layout
        and H2D transfer out of timed regions (KMeans.prepare idiom)."""
        sess, cfg = self.session, self.config
        w = sess.num_workers
        nb = w * cfg.num_model_slices
        vpb = -(-cfg.vocab // nb)
        if cfg.vocab_sub_block:
            # sub-block layout: the block width must split into whole
            # sub-blocks (extra slots are never-touched zero-count rows)
            vpb = lane_pack.round_up(vpb, cfg.vocab_sub_block)
        v_pad = vpb * nb
        num_docs = docs.shape[0]
        if num_docs % w:
            raise ValueError(f"num_docs {num_docs} must divide over {w} workers")
        if docs.size and (docs.min() < 0 or docs.max() >= cfg.vocab):
            raise ValueError(
                f"token ids must be in [0, {cfg.vocab}); got "
                f"[{docs.min()}, {docs.max()}]")

        from harp_tpu.models.sgd_mf import identity_assign, serpentine_assign

        if cfg.balance:
            word_block, word_slot = serpentine_assign(
                np.bincount(docs.reshape(-1), minlength=cfg.vocab), nb)
        else:
            word_block, word_slot = identity_assign(cfg.vocab, nb)

        if cfg.vocab_sub_block:
            docs_b, mask_b, lb, lbs = bucketize_tokens_subblock(
                docs, nb, vpb, cfg.vocab_sub_block, word_block, word_slot)
        else:
            docs_b, mask_b, lb = bucketize_tokens(docs, nb, vpb, word_block,
                                                  word_slot)
            lbs = 0
        d_local = num_docs // w
        nmb_eff = self._effective_minibatches(d_local)
        if nmb_eff == 1 and cfg.minibatches_per_hop > 1:
            # fully-parallel draws park the chain at a diffuse fixed point
            # (module doc: a word's tokens never coordinate); this happens
            # when docs-per-worker has no divisor within the budget (e.g. a
            # prime d_local) — surface it LOUDLY, not only in layout stats
            import warnings

            warnings.warn(
                f"LDA sub-stepping degraded to 1 (fully-parallel draws): "
                f"docs-per-worker {d_local} has no divisor <= "
                f"minibatches_per_hop={cfg.minibatches_per_hop}. Mixing "
                f"will be poor — pad num_docs so docs/worker gains a small "
                f"divisor (e.g. a multiple of "
                f"{cfg.minibatches_per_hop * w}).",
                RuntimeWarning, stacklevel=3)
        self.last_layout_stats = {
            "padded": int(docs_b.size), "tokens": int(docs.size),
            "overhead": docs_b.size / max(docs.size, 1),
            # sub-steps actually used: largest divisor of docs-per-worker that
            # fits the configured budget (prime d_local can degrade this to 1,
            # which weakens mixing — check this field if convergence stalls)
            "minibatches_per_hop": nmb_eff,
            # sub-block layout accounting (0/absent-width when off): the
            # bench reports this padding next to the throughput it buys
            "sub_block": cfg.vocab_sub_block,
            "sub_blocks_per_block": (vpb // cfg.vocab_sub_block
                                     if cfg.vocab_sub_block else 0),
        }
        rng = np.random.default_rng(seed)
        z0 = rng.integers(0, cfg.num_topics, docs_b.shape).astype(np.int32)
        # initial word-topic counts, laid out as NB stacked vocab blocks of
        # block-local slots
        wt = np.zeros((nb, vpb, cfg.num_topics), np.float32)
        blk = np.broadcast_to(np.arange(nb)[None, :, None],
                              docs_b.shape).reshape(-1)
        np.add.at(wt, (blk, docs_b.reshape(-1)),
                  np.eye(cfg.num_topics, dtype=np.float32)[z0.reshape(-1)]
                  * mask_b.reshape(-1, 1))
        if cfg.num_model_slices == 2:
            # worker i's shard = [a-block i; b-block w+i] stacked — the two
            # half-slices pipelined_rotation double-buffers
            wt = wt.reshape(2, w, vpb, cfg.num_topics).transpose(1, 0, 2, 3)
        wt = wt.reshape(v_pad, cfg.num_topics)
        if cfg.method == "cvb0":
            # soft assignments: one-hot init (same counts as the CGS init)
            z0 = (np.eye(cfg.num_topics, dtype=np.float32)[z0]
                  * mask_b[..., None])

        key = (w, v_pad, lb, num_docs, cfg.method, cfg.num_model_slices, lbs)
        if key not in self._fns:
            self._fns[key] = self._build(w, v_pad, lb, num_docs // w, lbs)
        return (key,
                (sess.scatter(jnp.asarray(docs_b, jnp.int32)),
                 sess.scatter(jnp.asarray(mask_b, jnp.float32)),
                 sess.scatter(jnp.asarray(z0)),
                 sess.scatter(jnp.asarray(wt))),
                jnp.asarray(seed, jnp.int32),
                (word_block, word_slot, vpb))

    def _out_rows(self, w: int, word_block: np.ndarray,
                  word_slot: np.ndarray, vpb: int) -> np.ndarray:
        """Row of each original vocab id in the scattered wt output: block
        b lives on worker b % w; with 2 slices the shard stacks [a; b]."""
        ns = self.config.num_model_slices
        owner = (word_block % w).astype(np.int64)
        sl = word_block // w
        return (owner * ns + sl) * vpb + word_slot

    def fit_prepared(self, state
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run training on already-placed device data (no host prep)."""
        import time as _time

        key, data, seed, (word_block, word_slot, vpb) = state
        t0 = _time.perf_counter()
        doc_topic, wt_out, z, ll = self._fns[key](*data, seed)
        ll = np.asarray(ll)
        wall = _time.perf_counter() - t0
        # telemetry at the ll fetch that was already here (per-epoch events,
        # wall amortized over the scanned program)
        telemetry.record_chunk(
            "lda", start=0, losses=ll.tolist(), wall_s=wall,
            ledger=telemetry.ledger_for(
                "lda", quant=self.config.quant,
                sub_block=bool(self.config.vocab_sub_block)))
        # un-permute word rows back to original vocab ids; fetch() gathers
        # sharded outputs across gang processes (run.py gang CLI)
        wt_out = fetch(wt_out)
        wt_final = wt_out[self._out_rows(key[0], word_block, word_slot, vpb)]
        return fetch(doc_topic), wt_final, ll

    def fit(self, docs: np.ndarray, seed: int = 0
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Train on a (num_docs, doc_len) token matrix.

        Returns (doc_topic (D, K), word_topic (V, K), log-likelihood per epoch
        in the reference formula)."""
        return self.fit_prepared(self.prepare(docs, seed))

    def fit_checkpointed(self, state, checkpointer, save_every: int = 1,
                         epochs: Optional[int] = None
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Train with periodic model checkpointing and automatic resume.

        Every ``save_every`` epochs the chain state — topic assignments ``z``
        and the word-topic counts ``wt`` (THE model: the reference dumped it
        per-N iterations via ``printModel``, LDAMPCollectiveMapper.java:125,
        351) — is saved; a populated checkpoint directory resumes from the
        newest epoch. Chunk boundaries stay on the ``save_every`` grid (plus
        the final epoch), and each chunk's RNG key derives from
        ``seed + start_epoch``, so a run killed at any checkpoint and resumed
        is bitwise identical to an uninterrupted fit_checkpointed run at the
        same ``save_every`` (the trajectory differs from a single full-scan
        :meth:`fit_prepared` only in the per-chunk RNG folding). Returns
        (doc_topic, word_topic-unpermuted, ll-for-run-epochs, start_epoch).

        World-size-agnostic: besides the chain state the checkpoint stores
        the blocked corpus layout (token slots + mask + vocab id maps) and a
        manifest meta naming the writing world. A resume under a different
        worker count (the supervisor's shrink/re-place relaunch) restores
        with the SAVED shapes and re-matches every token's assignment onto
        this session's blocking by its (doc, vocab-id) key
        (collectives.repartition.rematch_tokens — exact up to the
        exchangeability of same-word-same-doc occurrences, under which all
        Gibbs counts are invariant), then rebuilds the word-topic counts at
        the new layout. Same-world resume takes the historical bitwise path
        untouched."""
        sess, cfg = self.session, self.config
        key, data, seed, (word_block, word_slot, vpb) = state
        docs_b, mask_b, z_cur, wt_cur = data
        from harp_tpu.parallel import faults
        from harp_tpu.utils import checkpoint as ckpt_lib

        w, v_pad, lb, num_docs = key[:4]
        lbs = key[6] if len(key) > 6 else 0
        total = epochs if epochs is not None else cfg.epochs
        start = 0
        # the blocked-layout leaves ride in EVERY checkpoint so a DIFFERENT
        # world can recover (doc, vocab-id) per token; the corpus is static,
        # so these fetches happen once. Deliberate size tradeoff: each step
        # dir stays fully self-contained (the keep-last-N pruning and the
        # corrupt-step-skip fallback both assume any single step restores
        # alone), at the cost of re-writing the static layout (~2x the z
        # payload for CGS) per save
        layout_leaves = {
            "docs": fetch(docs_b),
            "mask": fetch(mask_b).astype(np.uint8),
            "word_block": np.asarray(word_block, np.int32),
            "word_slot": np.asarray(word_slot, np.int32),
        }
        # meta-less (pre-elastic) steps hold only {z, wt} — restore them
        # through the legacy template so same-world resume of an old work
        # dir keeps working (a world CHANGE on one raises the clear
        # no-metadata error in _repartition_chain)
        legacy_like = {"z": np.zeros(z_cur.shape, z_cur.dtype),
                       "wt": np.zeros(wt_cur.shape, wt_cur.dtype)}
        # verified resume, single read: manifest-checksummed steps only (a
        # corrupt newest checkpoint falls back to the previous step,
        # utils.checkpoint). `like` only conveys tree structure + dtypes:
        # host zeros, not a full D2H gather of the device arrays (advisor
        # r3). A step written at another world size restores through a
        # template with the SAVED shapes (its manifest meta).
        resume, saved, ck_meta = checkpointer.restore_latest_valid(
            like_from_meta=lambda m: (ckpt_lib.meta_like(m) if m
                                      else legacy_like),
            return_meta=True)
        if resume is not None:
            start = resume
            if ck_meta is not None and ck_meta.get("model") not in (None,
                                                                    "lda"):
                # the template followed the SAVED shapes, so the leaf-count
                # guard cannot catch a wrong-model work dir anymore — the
                # recorded model name does
                raise ValueError(
                    f"checkpoint in this work dir was written by model "
                    f"{ck_meta['model']!r}, not lda — wrong work dir?")
            if start > total:
                raise ValueError(
                    f"checkpoint at epoch {start} exceeds the requested "
                    f"{total} epochs (pass a fresh directory or a larger "
                    f"budget)")
            if (int(ck_meta["world"]) != w if ck_meta and "world" in ck_meta
                    else np.shape(saved["z"]) != tuple(z_cur.shape)):
                saved = self._repartition_chain(saved, ck_meta,
                                                layout_leaves, vpb,
                                                tuple(z_cur.shape))
            # the device reshard path hands back already-placed arrays in
            # this session's sharding — no host round trip to undo
            z_cur = (saved["z"] if isinstance(saved["z"], jax.Array)
                     else sess.scatter(jnp.asarray(saved["z"])))
            wt_cur = (saved["wt"] if isinstance(saved["wt"], jax.Array)
                      else sess.scatter(jnp.asarray(saved["wt"])))
        chunk_fns = {}
        lls = []
        doc_topic = None
        # telemetry: step events at the chunk boundaries' existing ll fetch
        ledger = telemetry.ledger_for(
            "lda", quant=cfg.quant, sub_block=bool(cfg.vocab_sub_block))
        import time as _time

        ep = start
        while ep < total:
            # iteration-boundary fault hook (parallel.faults)
            faults.fire(ep + 1, checkpointer)
            # stay on the save_every grid so an interrupted run's chunk
            # boundaries (hence per-chunk RNG keys) match an uninterrupted one
            chunk = min(save_every - ep % save_every, total - ep)
            if chunk not in chunk_fns:
                sub = LDA(sess, dataclasses.replace(cfg, epochs=chunk))
                chunk_fns[chunk] = sub._build(w, v_pad, lb, num_docs // w,
                                              lbs)
            t0 = _time.perf_counter()
            doc_topic, wt_cur, z_cur, ll = chunk_fns[chunk](
                docs_b, mask_b, z_cur, wt_cur,
                jnp.asarray(int(seed) + ep, jnp.int32))
            chunk_lls = np.asarray(ll).tolist()
            wall = _time.perf_counter() - t0
            lls.extend(chunk_lls)
            telemetry.record_chunk("lda", start=ep, losses=chunk_lls,
                                   wall_s=wall, ledger=ledger)
            ep += chunk
            with telemetry.phase("lda.checkpoint"):
                save_state = {"z": fetch(z_cur), "wt": fetch(wt_cur),
                              **layout_leaves}
                checkpointer.save(ep, save_state, meta=ckpt_lib.state_meta(
                    save_state, model="lda", world=w,
                    num_model_slices=cfg.num_model_slices, vpb=vpb,
                    vocab=cfg.vocab, method=cfg.method))
        if hasattr(checkpointer, "wait"):
            checkpointer.wait()       # surface a failed async final write
        wt_out = fetch(wt_cur)
        wt_final = wt_out[self._out_rows(w, word_block, word_slot, vpb)]
        if doc_topic is not None:
            dt = fetch(doc_topic)
        else:
            # checkpoint already covered every requested epoch: no chunk ran,
            # so rebuild doc_topic from the restored assignments z (counts of
            # each doc's unmasked tokens per topic — same formula as the
            # in-program init) instead of fabricating zeros
            z_h = fetch(z_cur)
            m_h = fetch(mask_b)
            if cfg.method == "cvb0":
                dt = (z_h * m_h[..., None]).sum(axis=(1, 2))
            else:
                dt = (np.eye(cfg.num_topics, dtype=np.float32)[z_h]
                      * m_h[..., None]).sum(axis=(1, 2))
        return dt, wt_final, np.asarray(lls, np.float32), start


    def _reshard_mode(self) -> str:
        from harp_tpu.collectives import reshard as rs

        return rs.resolve_mode(self.config.reshard,
                               self.session.num_workers)

    def _repartition_chain(self, saved: dict, ck_meta, new_layout: dict,
                           vpb: int, new_z_shape: tuple) -> dict:
        """Chain state written at another world size → this session's
        blocked layout. Every token's topic assignment is re-matched onto
        the new blocking by its (doc, vocab-id) key; word-topic counts
        follow their (word_block, word_slot) maps. Default
        (``LDAConfig.reshard``): both leaves move ON DEVICE through
        collectives/reshard.py — the token match is computed host-side on
        the INDEX arrays only (doc/vocab ids, not the payload), then z rows
        and wt rows ride chunk-bounded all_to_all rounds on the mesh;
        ``reshard="host"`` keeps the PR 8 numpy path (rematch_tokens + a
        count rebuild) as the parity oracle. (doc-topic, word-topic,
        topic-total) counts transfer EXACTLY either way, the only freedom
        being the exchangeable order of same-word-same-doc occurrences;
        2-slice blockings re-shard through the same worker-major half-slice
        placement the factors use. Once per resume — no collective enters
        any TRAINING step program (jaxlint JL201/JL203 budgets stay
        bitwise; the reshard program has its own pinned targets)."""
        from harp_tpu.collectives import repartition as rep
        from harp_tpu.collectives import reshard as rs

        cfg = self.config
        sess = self.session
        if ck_meta is None or "world" not in ck_meta:
            raise ValueError(
                "checkpoint does not match this session's chain shapes and "
                "carries no world metadata (written by a pre-elastic "
                "version?) — resume at the original worker count")
        if int(ck_meta.get("vocab", cfg.vocab)) != cfg.vocab \
                or str(ck_meta.get("method", cfg.method)) != cfg.method:
            raise ValueError(
                f"checkpoint chain (vocab={ck_meta.get('vocab')}, "
                f"method={ck_meta.get('method')}) does not describe this "
                f"model (vocab={cfg.vocab}, method={cfg.method})")
        old_world = int(ck_meta["world"])
        old_ns = int(ck_meta.get("num_model_slices", 1))
        new_ns = cfg.num_model_slices
        w = sess.num_workers
        saved_z = np.asarray(saved["z"])
        nb_old = saved_z.shape[1]
        vpb_old = int(ck_meta["vpb"])
        nb_new = int(new_z_shape[1])

        def inverse(wb, ws, nb, width):
            inv = np.full((nb, width), -1, np.int64)
            inv[np.asarray(wb, np.int64),
                np.asarray(ws, np.int64)] = np.arange(len(wb))
            return inv

        inv_old = inverse(saved["word_block"], saved["word_slot"], nb_old,
                          vpb_old)
        inv_new = inverse(new_layout["word_block"], new_layout["word_slot"],
                          nb_new, vpb)
        od, ob, op = np.nonzero(np.asarray(saved["mask"]) > 0)
        v_old = inv_old[ob, np.asarray(saved["docs"])[od, ob, op]]
        nd, nb_i, np_i = np.nonzero(np.asarray(new_layout["mask"]) > 0)
        slots_new = np.asarray(new_layout["docs"])[nd, nb_i, np_i]
        v_new = inv_new[nb_i, slots_new]
        if len(v_old) and v_old.min() < 0 or len(v_new) and v_new.min() < 0:
            raise ValueError(
                "blocked corpus references slots outside its vocab id maps "
                "— the checkpoint layout leaves are inconsistent")
        k = cfg.num_topics
        mode = self._reshard_mode()
        if mode in ("device", "ring"):
            schedule = "alltoall" if mode == "device" else "ring"
            chunk = cfg.reshard_chunk_bytes or rs.DEFAULT_CHUNK_BYTES
            # token match on the INDEX arrays (the rematch_tokens lexsort,
            # payload-free): the k-th (doc, vocab) occurrence on the old
            # side pairs with the k-th on the new side
            old_order = np.lexsort((v_old, od))
            new_order = np.lexsort((v_new, nd))
            if not (np.array_equal(od[old_order], nd[new_order])
                    and np.array_equal(v_old[old_order], v_new[new_order])):
                raise ValueError(
                    "checkpoint token multiset does not match the prepared "
                    "corpus — the resumed run was prepared on different "
                    "data than the checkpoint was written from")
            lb_old, lb_new = saved_z.shape[2], int(new_z_shape[2])
            src_pos = ((od * nb_old + ob) * lb_old + op)[old_order]
            dst_pos = ((nd * nb_new + nb_i) * lb_new + np_i)[new_order]
            row_elems = k if cfg.method == "cvb0" else 1
            plan = rs.plan_moves(
                src_pos, dst_pos, saved_z.shape[0] * nb_old * lb_old,
                int(new_z_shape[0]) * nb_new * lb_new, w,
                row_elems * saved_z.dtype.itemsize, chunk, schedule)
            z_new = rs.reshard(
                sess, saved_z, plan,
                sess.scatter(np.zeros(new_z_shape, saved_z.dtype)))
            # wt rows follow their word: moving row v verbatim IS the
            # rebuild (counts per (word, topic) are blocking-invariant)
            old_wt_lay = rs.block_layout(
                (np.asarray(saved["word_block"]),
                 np.asarray(saved["word_slot"])), vpb_old, old_world,
                old_ns)
            new_wt_lay = rs.block_layout(
                (np.asarray(new_layout["word_block"]),
                 np.asarray(new_layout["word_slot"])), vpb, w, new_ns)
            wt_new = rs.reshard_factor(
                sess, np.asarray(saved["wt"]), old_wt_lay, old_world,
                new_wt_lay, cfg.vocab,
                sess.scatter(np.zeros((nb_new * vpb, k), np.float32)),
                chunk_bytes=chunk, schedule=schedule)
            return {**saved, "z": z_new, "wt": wt_new}
        matched = rep.rematch_tokens(
            od, v_old, saved_z[od, ob, op], nd, v_new)
        z_new = np.zeros(new_z_shape, saved_z.dtype)
        z_new[nd, nb_i, np_i] = matched
        # rebuild word-topic counts at the new blocking (prepare's formula)
        contrib = (matched if cfg.method == "cvb0"
                   else np.eye(k, dtype=np.float32)[matched])
        wt = np.zeros((nb_new, vpb, k), np.float32)
        np.add.at(wt, (nb_i, slots_new), contrib)
        if new_ns == 2:
            # device order stacks worker-major half-slices (prepare's
            # 2-slice placement) — mirror it so the scatter lands right
            wt = wt.reshape(2, nb_new // 2, vpb, k).transpose(1, 0, 2, 3)
        return {**saved, "z": z_new, "wt": wt.reshape(nb_new * vpb, k)}


# --------------------------------------------------------------------------- #
# Oracles (host)
# --------------------------------------------------------------------------- #

def reference_log_likelihood(word_topic: np.ndarray, beta: float,
                             vocab: int) -> float:
    """The reference's likelihood formula on host counts (CalcLikelihoodTask +
    printLikelihood completion) — for tests and offline evaluation."""
    return _ref_ll_np(word_topic, beta, vocab)


def _lgamma(x):
    try:
        from scipy.special import gammaln
        return gammaln(x)
    except ImportError:
        from math import lgamma
        return np.vectorize(lgamma)(x)


def _ref_ll_np(word_topic: np.ndarray, beta: float, vocab: int) -> float:
    k = word_topic.shape[1]
    nz = word_topic > 0.5
    ll = float(np.sum(np.where(nz, _lgamma(word_topic + beta)
                               - _lgamma(beta), 0.0)))
    topic_tot = word_topic.sum(axis=0)
    ll -= float(np.sum(_lgamma(topic_tot + vocab * beta)))
    ll += k * float(_lgamma(np.asarray(vocab * beta)))
    return ll


def full_model_log_likelihood(doc_topic: np.ndarray, word_topic: np.ndarray,
                              alpha: float, beta: float, vocab: int) -> float:
    """Full MALLET model log-likelihood: the reference's word part plus the
    doc-topic term it omits (ParallelTopicModel.modelLogLikelihood)."""
    k = doc_topic.shape[1]
    ll = _ref_ll_np(word_topic, beta, vocab)
    nz = doc_topic > 0.5
    ll += float(np.sum(np.where(nz, _lgamma(doc_topic + alpha)
                                - _lgamma(alpha), 0.0)))
    ll -= float(np.sum(_lgamma(doc_topic.sum(axis=1) + k * alpha)))
    ll += doc_topic.shape[0] * float(_lgamma(np.asarray(k * alpha)))
    return ll


def sequential_cgs_reference(docs: np.ndarray, cfg: LDAConfig, seed: int = 0
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Single-device token-sequential CGS — the convergence-parity oracle.

    Returns (doc_topic, word_topic, per-epoch reference log-likelihood)."""
    rng = np.random.default_rng(seed)
    d, l = docs.shape
    k, v = cfg.num_topics, cfg.vocab
    z = rng.integers(0, k, (d, l))
    ndk = np.zeros((d, k))
    nwk = np.zeros((v, k))
    nk = np.zeros(k)
    for di in range(d):
        for li in range(l):
            t = z[di, li]
            ndk[di, t] += 1
            nwk[docs[di, li], t] += 1
            nk[t] += 1
    lls = []
    for _ in range(cfg.epochs):
        for di in range(d):
            for li in range(l):
                wi, t = docs[di, li], z[di, li]
                ndk[di, t] -= 1
                nwk[wi, t] -= 1
                nk[t] -= 1
                p = ((ndk[di] + cfg.alpha) * (nwk[wi] + cfg.beta)
                     / (nk + v * cfg.beta))
                t = rng.choice(k, p=p / p.sum())
                z[di, li] = t
                ndk[di, t] += 1
                nwk[wi, t] += 1
                nk[t] += 1
        lls.append(_ref_ll_np(nwk, cfg.beta, v))
    return ndk, nwk, np.asarray(lls)
