"""LDA via collapsed Gibbs sampling with model rotation.

Reference parity: ml/java lda (LDAMPCollectiveMapper.java:51 — SparseLDA CGS with
the word-topic table ring-rotating via Rotator:257 and doc-topic tables local;
likelihood via allreduce:731 — BASELINE's "harp-java CGS-LDA, dynamic scheduler +
asynchronous rotation") and contrib/lda (CVB0).

TPU-native reformulation (SURVEY §7 "hard parts" — async semantics under SPMD):

* Docs are sharded over workers; the word-topic count matrix is split into W
  vocab blocks that ring-rotate (``ppermute``) — Harp's Rotator schedule.
* Strictly sequential per-token Gibbs is hostile to SPMD, so sampling is
  **blocked**: during a hop, every token of the resident vocab block draws its
  topic from the CURRENT counts in parallel; count deltas are applied after the
  block (one-hot matmuls on the MXU). This is the standard blocked/stale-count
  approximation used by every distributed CGS (including Harp itself across
  workers — its staleness is per-rotation too, LDAMPCollectiveMapper rotates
  between updates); convergence is statistical, not token-sequential.
* Topic totals n_k are refreshed by psum once per hop — bounded staleness,
  replacing Harp's asynchronously drifting totals.

Likelihood monitor: the model's per-epoch joint log-likelihood terms that depend
on counts (word-topic part), allreduced — matching the reference's
printLogLikelihood role rather than its exact formula.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.collectives import lax_ops, rotation
from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.session import HarpSession


@dataclasses.dataclass(frozen=True)
class LDAConfig:
    """Reference CLI parity (numTopics, alpha, beta, numIterations)."""

    num_topics: int = 10
    vocab: int = 100
    alpha: float = 0.1
    beta: float = 0.01
    epochs: int = 20
    method: str = "cgs"         # "cgs" (ml/java lda) or "cvb0" (contrib/lda)


def bucketize_tokens(docs: np.ndarray, num_blocks: int, vpb: int
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-side layout: (D, L) tokens → (D, W, Lb) grouped by home vocab block.

    Each hop then processes exactly the resident block's tokens (padded to the
    max per-(doc, block) count Lb) instead of sampling every token every hop.
    """
    d, l = docs.shape
    rows = np.arange(d)[:, None]
    block = np.minimum(docs // vpb, num_blocks - 1)
    counts = np.zeros((d, num_blocks), np.int64)
    np.add.at(counts, (rows, block), 1)
    lb = max(int(counts.max()), 1)
    # padding slots hold each block's first word id (in-range for w_local);
    # mask zeroes their effect on counts and sampling
    base = (np.arange(num_blocks) * vpb).astype(docs.dtype)
    docs_b = np.broadcast_to(base[None, :, None], (d, num_blocks, lb)).copy()
    mask_b = np.zeros((d, num_blocks, lb), np.float32)
    order = np.argsort(block, axis=1, kind="stable")
    sorted_block = np.take_along_axis(block, order, axis=1)
    sorted_docs = np.take_along_axis(docs, order, axis=1)
    bucket_starts = np.concatenate(
        [np.zeros((d, 1), np.int64), np.cumsum(counts, axis=1)[:, :-1]], axis=1)
    pos = np.arange(l)[None, :] - bucket_starts[rows, sorted_block]
    docs_b[rows, sorted_block, pos] = sorted_docs
    mask_b[rows, sorted_block, pos] = 1.0
    return docs_b, mask_b, lb


class LDA:
    """Distributed CGS-LDA over a HarpSession mesh."""

    def __init__(self, session: HarpSession, config: LDAConfig):
        if config.method not in ("cgs", "cvb0"):
            raise ValueError(f"method must be 'cgs' or 'cvb0', got "
                             f"{config.method!r}")
        self.session = session
        self.config = config
        self._fns = {}

    def _build(self, w: int, v_pad: int, lb: int):
        cfg = self.config
        k = cfg.num_topics
        vpb = v_pad // w                      # vocab per block

        def fit_fn(docs_b, mask_b, z0, wt_block0, seed):
            # docs_b/mask_b/z0: (D_local, W, Lb) — tokens pre-bucketed by home
            # vocab block (host-side, bucketize_tokens), so each hop touches
            # only the resident block's tokens instead of sampling all tokens
            # and discarding (w-1)/w of the draws.
            def hop_body(carry, wt_block, t):
                doc_topic, z, topic_tot, key = carry
                wid = lax_ops.worker_id()
                src = (wid - t) % w           # home block of resident slice
                docs_s = jnp.take(docs_b, src, axis=1)        # (D, Lb)
                mask_s = jnp.take(mask_b, src, axis=1)
                w_local = docs_s - src * vpb

                # blocked update: resident-block tokens update from current
                # counts: p(z=k) ∝ (n_dk−cur+α)(n_wk−cur+β)/(n_k−cur+Vβ)
                if cfg.method == "cvb0":
                    # z carries SOFT assignments gamma (D, W, Lb, K)
                    cur = jnp.take(z, src, axis=1) * mask_s[..., None]
                else:
                    z_s = jnp.take(z, src, axis=1)
                    cur = (jax.nn.one_hot(z_s, k, dtype=jnp.float32)
                           * mask_s[..., None])               # (D, Lb, K)
                nd = doc_topic[:, None, :] - cur              # exclude self
                nw = wt_block[w_local] - cur
                nk = topic_tot[None, None, :] - cur
                logits = (jnp.log(jnp.maximum(nd + cfg.alpha, 1e-10))
                          + jnp.log(jnp.maximum(nw + cfg.beta, 1e-10))
                          - jnp.log(jnp.maximum(nk + cfg.vocab * cfg.beta,
                                                1e-10)))
                if cfg.method == "cvb0":
                    # CVB0 (contrib/lda CVB0 LdaMapCollective): deterministic
                    # mean-field update — soft assignment = normalized
                    # probabilities instead of a sample
                    new = jax.nn.softmax(logits, axis=-1) * mask_s[..., None]
                    z = jnp.where(
                        (jnp.arange(w) == src)[None, :, None, None],
                        new[:, None, :, :], z)
                else:
                    key, sub = jax.random.split(key)
                    z_new = jax.random.categorical(sub, logits, axis=-1)
                    new = (jax.nn.one_hot(z_new, k, dtype=jnp.float32)
                           * mask_s[..., None])
                    z = jnp.where((jnp.arange(w) == src)[None, :, None],
                                  z_new[:, None, :], z)
                delta = new - cur                             # (D, Lb, K)
                doc_topic = doc_topic + delta.sum(axis=1)
                wt_block = wt_block + jax.ops.segment_sum(
                    delta.reshape(-1, k), w_local.reshape(-1), num_segments=vpb)
                # bounded-staleness topic totals: refresh by psum of deltas
                topic_tot = topic_tot + jax.lax.psum(delta.sum(axis=(0, 1)),
                                                     lax_ops.WORKERS)
                return (doc_topic, z, topic_tot, key), wt_block

            key = jax.random.fold_in(jax.random.PRNGKey(0),
                                     seed + lax_ops.worker_id())
            if cfg.method == "cvb0":
                doc_topic = (z0 * mask_b[..., None]).sum(axis=(1, 2))
            else:
                doc_topic = (jax.nn.one_hot(z0, k, dtype=jnp.float32)
                             * mask_b[..., None]).sum(axis=(1, 2))
            topic_tot = jax.lax.psum(doc_topic.sum(axis=0), lax_ops.WORKERS)

            def epoch(state, _):
                doc_topic, z, topic_tot, wt, key = state
                (doc_topic, z, topic_tot, key), wt = rotation.rotate_scan(
                    hop_body, (doc_topic, z, topic_tot, key), wt, w)
                # log-likelihood proxy: Σ lgamma(n_wk+β) − Σ lgamma(n_k+Vβ)
                ll_w = jax.lax.psum(
                    jnp.sum(jax.scipy.special.gammaln(wt + cfg.beta)),
                    lax_ops.WORKERS)
                ll_k = jnp.sum(jax.scipy.special.gammaln(
                    topic_tot + cfg.vocab * cfg.beta))
                return (doc_topic, z, topic_tot, wt, key), ll_w - ll_k

            (doc_topic, z, topic_tot, wt, key), ll = jax.lax.scan(
                epoch, (doc_topic, z0, topic_tot, wt_block0, key), None,
                length=cfg.epochs)
            return doc_topic, wt, z, ll

        sess = self.session
        return sess.spmd(
            fit_fn,
            in_specs=(sess.shard(), sess.shard(), sess.shard(), sess.shard(),
                      sess.replicate()),
            out_specs=(sess.shard(), sess.shard(), sess.shard(),
                       sess.replicate()),
        )

    def fit(self, docs: np.ndarray, seed: int = 0
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Train on a (num_docs, doc_len) token matrix.

        Returns (doc_topic (D, K), word_topic (V, K), log-likelihood per epoch).
        """
        sess, cfg = self.session, self.config
        w = sess.num_workers
        v_pad = -(-cfg.vocab // w) * w
        num_docs = docs.shape[0]
        if num_docs % w:
            raise ValueError(f"num_docs {num_docs} must divide over {w} workers")

        docs_b, mask_b, lb = bucketize_tokens(docs, w, v_pad // w)
        rng = np.random.default_rng(seed)
        z0 = rng.integers(0, cfg.num_topics, docs_b.shape).astype(np.int32)
        # initial word-topic counts, laid out as W stacked vocab blocks
        wt = np.zeros((v_pad, cfg.num_topics), np.float32)
        np.add.at(wt, docs_b.reshape(-1),
                  np.eye(cfg.num_topics, dtype=np.float32)[z0.reshape(-1)]
                  * mask_b.reshape(-1, 1))
        if cfg.method == "cvb0":
            # soft assignments: one-hot init (same counts as the CGS init)
            z0 = (np.eye(cfg.num_topics, dtype=np.float32)[z0]
                  * mask_b[..., None])

        key = (w, v_pad, lb, num_docs, cfg.method)
        if key not in self._fns:
            self._fns[key] = self._build(w, v_pad, lb)
        doc_topic, wt_out, z, ll = self._fns[key](
            sess.scatter(jnp.asarray(docs_b, jnp.int32)),
            sess.scatter(jnp.asarray(mask_b, jnp.float32)),
            sess.scatter(jnp.asarray(z0)),
            sess.scatter(jnp.asarray(wt)),
            jnp.asarray(seed, jnp.int32))
        return (np.asarray(doc_topic), np.asarray(wt_out)[: cfg.vocab],
                np.asarray(ll))
