#!/usr/bin/env python
"""Hot-path scatter lint: flag ``.at[...].add/.set/...`` in device code.

XLA lowers indexed updates to the TPU scatter unit, which serializes at
~8.5 ns per 128-byte row — measured 8.8× slower than the one-hot-GEMM form
on the CSR K-means densify and 82% of the whole LDA hop before the r5 fix
(PERF.md). Every hot path in this repo therefore routes scatters through
``harp_tpu/ops/lane_pack.py`` (gemm_scatter / densify_rows); a NEW
``.at[...].add`` in ``harp_tpu/models/`` or ``harp_tpu/ops/`` is far more
likely to be a perf bug than a deliberate choice.

This checker walks the AST of both trees and reports every indexed-update
call that is not on the explicit allowlist below. Cold paths that
legitimately scatter (one-time prepare-side layout, O(K)-sized solver
bookkeeping, gated legacy strategies kept for very-sparse regimes) are
allowlisted **by (file, enclosing function)** with the reason inline — so
the next reader knows why each exemption is sound, and a new scatter in an
allowlisted FILE but a different function still trips the lint.

Usage: ``python tools/lint_scatter.py [repo_root]`` — exits nonzero on any
violation. ``tests/test_lint_scatter.py`` runs it in tier-1.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, NamedTuple, Optional

# indexed-update methods XLA lowers to scatter ops
_SCATTER_METHODS = {"add", "set", "mul", "divide", "min", "max", "power",
                    "apply"}

# directories under the repo root whose device code the lint covers
HOT_TREES = (os.path.join("harp_tpu", "models"),
             os.path.join("harp_tpu", "ops"))

# (relative path, enclosing function) -> why the scatter is legitimate.
# Everything here is COLD (runs once per prepare/build, not per iteration)
# or an explicitly-gated legacy strategy whose hot replacement exists.
ALLOWLIST = {
    ("harp_tpu/models/sgd_mf.py", "densify"):
        "prepare-time slab densification: runs ONCE per layout, scatters "
        "into a slab too wide for a one-hot GEMM (slab_elems lanes); the "
        "per-epoch hot path is pure stripe GEMMs",
    ("harp_tpu/models/sgd_mf.py", "mb_step"):
        "legacy layout='sparse' minibatch update, kept for data too large "
        "to densify; documented ~25M samples/s gather/scatter wall — the "
        "dense masked-stripe layout IS the hot path",
    ("harp_tpu/models/sparse.py", "sparse_kmeans_stats"):
        "strategy='gather' phantom-count correction: the gated legacy "
        "strategy for very-sparse-very-wide data (default is the "
        "lane_pack densify-GEMM, 13x faster on the bench shape)",
    ("harp_tpu/models/solvers.py", "bwd"):
        "L-BFGS two-loop recursion alpha write: O(history) scalars per "
        "OUTER optimizer step, not per-sample work",
    ("harp_tpu/models/solvers.py", "step"):
        "L-BFGS (s, y, rho) ring-buffer history write: O(history) rows "
        "per outer step",
    ("harp_tpu/models/forest.py", "one_tree"):
        "per-tree feature mask init: O(dim) bits once per tree build, "
        "never inside the per-sample scoring loop",
    ("harp_tpu/ops/linalg.py", "body"):
        "distributed-sort permutation bookkeeping: O(W) control-plane "
        "rows per merge round, not data-plane traffic",
}


class Violation(NamedTuple):
    path: str
    line: int
    func: str
    method: str

    def __str__(self):
        return (f"{self.path}:{self.line}: .at[...].{self.method} in "
                f"{self.func}() — route through ops/lane_pack "
                f"(gemm_scatter/densify_rows) or allowlist it in "
                f"tools/lint_scatter.py with a reason")


def _is_at_indexed_update(node: ast.Call) -> Optional[str]:
    """Matches ``<expr>.at[<idx>].<method>(...)``; returns the method name."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _SCATTER_METHODS):
        return None
    sub = f.value
    if not isinstance(sub, ast.Subscript):
        return None
    base = sub.value
    if isinstance(base, ast.Attribute) and base.attr == "at":
        return f.attr
    return None


def _scan_source(src: str, rel_path: str) -> List[Violation]:
    tree = ast.parse(src, filename=rel_path)
    out: List[Violation] = []

    func_stack: List[str] = []

    class V(ast.NodeVisitor):
        def _visit_func(self, node):
            func_stack.append(node.name)
            self.generic_visit(node)
            func_stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node):
            m = _is_at_indexed_update(node)
            if m is not None:
                func = func_stack[-1] if func_stack else "<module>"
                if (rel_path, func) not in ALLOWLIST:
                    out.append(Violation(rel_path, node.lineno, func, m))
            self.generic_visit(node)

    V().visit(tree)
    return out


def check(repo_root: str) -> List[Violation]:
    """Scan the hot trees; returns all un-allowlisted indexed updates."""
    violations: List[Violation] = []
    for tree_rel in HOT_TREES:
        tree_abs = os.path.join(repo_root, tree_rel)
        for name in sorted(os.listdir(tree_abs)):
            if not name.endswith(".py"):
                continue
            abs_path = os.path.join(tree_abs, name)
            rel = os.path.join(tree_rel, name).replace(os.sep, "/")
            with open(abs_path, encoding="utf-8") as f:
                violations.extend(_scan_source(f.read(), rel))
    return violations


def stale_allowlist_entries(repo_root: str) -> List[str]:
    """Allowlist rows whose (file, function) no longer scatters — entries
    must be pruned when the exempted code is fixed, or they rot into
    blanket exemptions."""
    live = set()
    for tree_rel in HOT_TREES:
        tree_abs = os.path.join(repo_root, tree_rel)
        for name in sorted(os.listdir(tree_abs)):
            if not name.endswith(".py"):
                continue
            rel = os.path.join(tree_rel, name).replace(os.sep, "/")
            with open(os.path.join(tree_abs, name), encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=rel)
            stack: List[str] = []

            class V(ast.NodeVisitor):
                def _visit_func(self, node):
                    stack.append(node.name)
                    self.generic_visit(node)
                    stack.pop()

                visit_FunctionDef = _visit_func
                visit_AsyncFunctionDef = _visit_func

                def visit_Call(self, node):
                    if _is_at_indexed_update(node) is not None:
                        live.add((rel, stack[-1] if stack else "<module>"))
                    self.generic_visit(node)

            V().visit(tree)
    return [f"{p}::{fn}" for (p, fn) in sorted(ALLOWLIST)
            if (p, fn) not in live]


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = check(root)
    for v in violations:
        print(str(v))
    stale = stale_allowlist_entries(root)
    for s in stale:
        print(f"stale allowlist entry (no scatter there anymore — prune "
              f"it): {s}")
    if not violations and not stale:
        print("scatter lint: clean")
    return 1 if (violations or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
