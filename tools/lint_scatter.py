#!/usr/bin/env python
"""Legacy CLI shim for the hot-path scatter lint — now jaxlint's JL106.

The r6 standalone checker was folded into ``tools/jaxlint`` (ISSUE 5): the
scatter rule lives in ``tools/jaxlint/checkers_ast.py::check_scatter`` and
its exemptions moved — same functions, same reasons — into the shared
``tools/jaxlint/allowlist.py`` keyed ``(file, function, "JL106")``. This
shim keeps the old entry points working:

* ``python tools/lint_scatter.py [repo_root]`` — same CLI, same exit codes;
* ``check`` / ``stale_allowlist_entries`` / ``_scan_source`` / ``ALLOWLIST``
  — the API ``tests/test_lint_scatter.py`` exercises.

New exemptions go in tools/jaxlint/allowlist.py, not here.
"""

from __future__ import annotations

import os
import sys
from typing import List, NamedTuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.jaxlint import checkers_ast as _ca              # noqa: E402
from tools.jaxlint.allowlist import ALLOWLIST as _SHARED   # noqa: E402
from tools.jaxlint.core import (iter_py_files,             # noqa: E402
                                run_ast_checkers)

# The legacy (file, function) -> reason view of the shared JL106 entries.
ALLOWLIST = {(path, func): why
             for (path, func, code), why in _SHARED.items()
             if code == "JL106"}

HOT_TREES = _ca.HOT_TREES


class Violation(NamedTuple):
    path: str
    line: int
    func: str
    method: str

    def __str__(self):
        return (f"{self.path}:{self.line}: .at[...].{self.method} in "
                f"{self.func}() — route through ops/lane_pack "
                f"(gemm_scatter/densify_rows) or allowlist it in "
                f"tools/jaxlint/allowlist.py with a reason")


def _scan_source(src: str, rel_path: str) -> List[Violation]:
    import ast

    tree = ast.parse(src, filename=rel_path)
    out = []
    for f in _ca.check_scatter(tree, rel_path, src):
        if (f.path, f.func) in ALLOWLIST:
            continue
        # each finding's message leads with its own ".at[...].<method>"
        # token, so the method is exact even with several updates per line
        method = f.message.split(" ", 1)[0].rsplit(".", 1)[1]
        out.append(Violation(f.path, f.line, f.func, method))
    return out


def check(repo_root: str) -> List[Violation]:
    """Scan the hot trees; returns all un-allowlisted indexed updates."""
    out = []
    for rel, src in iter_py_files(repo_root):
        if rel.startswith(HOT_TREES):
            out.extend(_scan_source(src, rel))
    return out


def stale_allowlist_entries(repo_root: str) -> List[str]:
    """Allowlist rows whose (file, function) no longer scatters."""
    live = {(f.path, f.func)
            for f in run_ast_checkers(repo_root, [_ca.check_scatter])}
    return [f"{p}::{fn}" for (p, fn) in sorted(ALLOWLIST)
            if (p, fn) not in live]


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = argv[0] if argv else _REPO
    violations = check(root)
    for v in violations:
        print(str(v))
    stale = stale_allowlist_entries(root)
    for s in stale:
        print(f"stale allowlist entry (no scatter there anymore — prune "
              f"it): {s}")
    if not violations and not stale:
        print("scatter lint: clean")
    return 1 if (violations or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
