#!/usr/bin/env python
"""Claims honesty check: README/PERF headline throughput numbers must match
the latest committed bench record.

VERDICT r5 #8: PERF.md claimed "every BASELINE workload clears the ... bound
by >=6x" while the official record read ALS 5.22 and LDA 5.44 — numeric
prose drifts the moment a number is retyped instead of checked. This tool
pins every headline claim to the committed ``BENCH_local.json``: each entry
below names the doc, a regex whose single capture group is the claimed
number (K/M/G/B suffixes understood), where the recorded value lives in the
bench record, and the relative band the claim must sit inside (default 10%
— wider than any committed spread column, narrower than any real drift
class; entries quoting run-to-run bands in prose still check their headline
number).

Failure modes are all loud:
  * claimed number outside the band          → the prose drifted (or the
    record moved and the prose was not updated with it);
  * regex no longer matches the doc          → stale checker entry (the
    claim was reworded without updating this table — same rule as
    lint_scatter's stale-allowlist check);
  * bench value missing or null              → the claim asserts a number
    the committed record does not (yet) back — unmeasured rows must not be
    quoted as measured.

Usage: ``python tools/check_claims.py [repo_root]`` — exits nonzero on any
violation. ``tests/test_check_claims.py`` runs it in tier-1.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Callable, List, NamedTuple, Optional, Union

_SUFFIX = {"K": 1e3, "M": 1e6, "G": 1e9, "B": 1e9}

BENCH_FILE = "BENCH_local.json"


class Claim(NamedTuple):
    claim_id: str
    doc: str                    # repo-relative doc path
    pattern: str                # regex; group(1) = the claimed number
    source: Union[tuple, Callable]   # key path into the record, or a
    #   callable(record) -> float for derived quantities (e.g. Xeon lbs)
    rel_tol: float = 0.10
    file: str = BENCH_FILE      # which committed record backs the claim:
    #   BENCH_local.json (measured rates) or tools/collective_budget.json
    #   (traced per-step comm volumes — exact, so those claims use tol 0)


def _xeon_lb(rate_key: str, anchor_key: str):
    return lambda b: b[rate_key]["rate"] / b[anchor_key] / 36.0


CLAIMS: List[Claim] = [
    # README headline table ("Headline rows from the committed benchmark
    # record") — one claim per row that states a number
    Claim("kmeans_flagship", "README.md",
          r"\| K-means regroupallgather \(flagship\) \|[^|]*\| (\S+) iters/s",
          ("kmeans", "rate")),
    Claim("sgd_mf", "README.md",
          r"\| SGD-MF dense masked-stripe \|[^|]*\| (\S+) ratings/s",
          ("sgd_mf", "rate")),
    Claim("lda", "README.md",
          r"\| CGS-LDA \(gemm_scatter count writes\) \|[^|]*\| (\S+) "
          r"tokens/s",
          ("lda", "rate")),
    Claim("lda_clueweb", "README.md",
          r"\| CGS-LDA clueweb-regime \|[^|]*\| (\S+) tokens/s",
          ("lda_large", "rate")),
    Claim("als", "README.md",
          r"\| ALS implicit \(pallas lane Cholesky\) \|[^|]*\| (\S+) "
          r"iters/s",
          ("als", "rate")),
    Claim("pca", "README.md",
          r"\| PCA correlation \|[^|]*\| (\S+) fits/s",
          ("pca", "rate")),
    Claim("nn", "README.md",
          r"\| Mini-batch NN \|[^|]*\| (\S+) samples/s",
          ("nn", "rate")),
    Claim("attention", "README.md",
          r"\| Flash attention \(pallas\) \|[^|]*\| (\S+) tokens/s",
          ("attention", "rate")),
    Claim("kmeans_csr", "README.md",
          r"\| K-means CSR densify / CSR covariance \|[^|]*\| (\S+) iters/s",
          ("kmeans_csr", "rate")),
    Claim("csr_cov", "README.md",
          r"\| K-means CSR densify / CSR covariance \|[^|]*\|[^|]*iters/s "
          r"/ (\S+) passes/s",
          ("csr_covariance", "rate")),
    Claim("native_parse", "README.md",
          r"\| Native CSV parse \|[^|]*\| (\S+) MB/s",
          ("kmeans_from_files", "load_native_mb_per_sec")),
    # README architecture-table prose rates
    Claim("sgd_mf_arch_row", "README.md",
          r"fused pallas hop — (\S+) samples/s on one v5e chip",
          ("sgd_mf", "rate")),
    Claim("lda_arch_row", "README.md",
          r"bitwise-exact, 2× the hop — (\S+) tokens/s on one chip",
          ("lda", "rate")),
    Claim("kmeans_csr_arch_row", "README.md",
          r"scatter-free block-densify-GEMM default — (\S+) iters/s on chip",
          ("kmeans_csr", "rate")),
    # PERF.md: the smallest Xeon lower bound, stated per workload (the
    # ">=6x" drift class this checker exists to kill)
    Claim("min_xeon_lb_als", "PERF.md",
          r"workloads: ALS (\S+)×",
          _xeon_lb("als", "als_cpu_anchor_iters_per_sec")),
    Claim("min_xeon_lb_lda", "PERF.md",
          r"workloads: ALS \S+×, LDA (\S+)×",
          _xeon_lb("lda", "lda_cpu_anchor_tokens_per_sec")),
    # PERF.md r8 comm-volume stage math: per-step collective operand bytes
    # at tier-1 shapes, pinned to the traced manifest (jaxlint JL203 keeps
    # the manifest honest; this table keeps the PROSE honest). Traced bytes
    # are exact — zero tolerance.
    Claim("comm_kmeans_allreduce_f32", "PERF.md",
          r"K-means allreduce \(W=8 tier-1\) \| (\S+) B",
          ("targets", "kmeans_allreduce", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_kmeans_allreduce_int8", "PERF.md",
          r"K-means allreduce \(W=8 tier-1\) \| \S+ B \| (\S+) B",
          ("targets", "kmeans_allreduce_int8", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_kmeans_rga_f32", "PERF.md",
          r"K-means regroupallgather \| (\S+) B",
          ("targets", "kmeans_regroupallgather", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_kmeans_rga_bf16", "PERF.md",
          r"K-means regroupallgather \| \S+ B \| (\S+) B",
          ("targets", "kmeans_regroupallgather_bf16", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_sgd_mf_f32", "PERF.md",
          r"SGD-MF rotation hop \| (\S+) B",
          ("targets", "sgd_mf_dense", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_sgd_mf_int8", "PERF.md",
          r"SGD-MF rotation hop \| \S+ B \| (\S+) B",
          ("targets", "sgd_mf_dense_int8", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # PERF.md r10 fused ring-DMA table: per-step wire bytes + the share
    # moved by in-kernel DMA, pinned to the traced manifest's fused rows
    # (a fused target reverting to ppermute changes the manifest and
    # fails jaxlint; this keeps the PROSE tied to the same numbers).
    Claim("comm_lda_f32_baseline", "PERF.md",
          r"LDA CGS hop \(f32 ppermute baseline\) \| (\S+) B",
          ("targets", "lda_cgs", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_lda_fused_total", "PERF.md",
          r"LDA CGS hop, fused \(lda_cgs_fused\) \| (\S+) B",
          ("targets", "lda_cgs_fused", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_lda_fused_dma", "PERF.md",
          r"LDA CGS hop, fused \(lda_cgs_fused\) \| \S+ B \| (\S+) B",
          ("targets", "lda_cgs_fused", "fused_dma_bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_lda_quantwt", "PERF.md",
          r"LDA CGS hop, quantized wt \(lda_cgs_quantwt_int8\) \| (\S+) B",
          ("targets", "lda_cgs_quantwt_int8", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_sgd_fused_total", "PERF.md",
          r"SGD-MF rotation hop, fused \(sgd_mf_dense_fused\) \| (\S+) B",
          ("targets", "sgd_mf_dense_fused", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_sgd_fused_dma", "PERF.md",
          r"SGD-MF rotation hop, fused \(sgd_mf_dense_fused\) \| \S+ B "
          r"\| (\S+) B",
          ("targets", "sgd_mf_dense_fused", "fused_dma_bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # README "Online serving" + PERF.md r11 (ISSUE 10): the committed
    # CPU-mesh serving latency/QPS rows (the bench group always measures —
    # the router/batcher stack is host-side; the on-chip re-measure
    # rewrites the record AND must update this prose, by design), plus the
    # serve dispatch byte pins against the traced manifest (exact, tol 0 —
    # the classify dispatch is pinned at ZERO collective bytes).
    Claim("serving_mixed_p50", "README.md",
          r"mixed traffic p50 (\S+) ms",
          ("serving", "mixes", "mixed", "p50_ms")),
    Claim("serving_mixed_p99", "README.md",
          r"mixed traffic p50 \S+ ms\s*/ p99 (\S+) ms",
          ("serving", "mixes", "mixed", "p99_ms")),
    Claim("serving_mixed_qps", "README.md",
          r"at (\S+) QPS",
          ("serving", "mixes", "mixed", "qps")),
    Claim("serving_perf_topk_heavy_p50", "PERF.md",
          r"\| topk_heavy \(0\.8\) \| (\S+) ms",
          ("serving", "mixes", "topk_heavy", "p50_ms")),
    Claim("serving_perf_mixed_p50", "PERF.md",
          r"\| mixed \(0\.5\) \| (\S+) ms",
          ("serving", "mixes", "mixed", "p50_ms")),
    Claim("serving_perf_mixed_qps", "PERF.md",
          r"\| mixed \(0\.5\) \| \S+ ms \| \S+ ms \| (\S+) \|",
          ("serving", "mixes", "mixed", "qps")),
    # PERF.md r13 (ISSUE 12): the serving-plane observability rows — the
    # per-stage latency breakdown from sampled request spans and its
    # reconciliation against the measured end-to-end (the stage durations
    # partition each span, so the mean ratio is ~1.0 by construction and
    # the p50 ratio sits inside a stated 25% band; both are pinned here so
    # the prose can never quote a breakdown the record doesn't back).
    Claim("serving_stage_coalesce_p50", "PERF.md",
          r"\| coalesce wait \| (\S+) ms",
          ("serving", "stage_breakdown", "coalesce", "p50_ms")),
    Claim("serving_stage_dispatch_p50", "PERF.md",
          r"\| dispatch \(resident compiled fn\) \| (\S+) ms",
          ("serving", "stage_breakdown", "dispatch", "p50_ms")),
    Claim("serving_stage_reply_hop_p50", "PERF.md",
          r"\| reply hop \| (\S+) ms",
          ("serving", "stage_breakdown", "reply_hop", "p50_ms")),
    Claim("serving_span_mean_ratio", "PERF.md",
          r"stage-mean sum / span mean = (\S+)",
          ("serving", "reconciliation", "mean_ratio"), rel_tol=0.02),
    Claim("serving_span_p50_ratio", "PERF.md",
          r"stage-p50 sum / span p50 = (\S+)",
          ("serving", "reconciliation", "p50_ratio")),
    # PERF.md r15 (ISSUE 14): the serving-fleet rows — recovery blip
    # (separate-process gang, scripted kill, reshard-engine spare
    # restore), refresh-under-load, and the hot-key cache's hot-subset
    # tail. The recovery timings vary run to run (subprocess start +
    # compile), so those bands are wider; the zero-failure counts are
    # asserted by the bench itself and tier-1, not here.
    Claim("fleet_recovery_steady_p99", "PERF.md",
          r"steady p99 (\S+) ms; controller-side",
          ("serving_fleet", "recovery", "steady", "p99_ms")),
    Claim("fleet_recovery_controller_s", "PERF.md",
          r"placement pushed\) (\S+) s; observed",
          ("serving_fleet", "recovery", "recovery_s"), rel_tol=0.5),
    Claim("fleet_recovery_observed_s", "PERF.md",
          r"recovery window (\S+) s end-to-end",
          ("serving_fleet", "recovery", "observed_recovery_s"),
          rel_tol=0.5),
    Claim("fleet_recovery_blip_p99", "PERF.md",
          r"p99 (\S+) ms — the blip",
          ("serving_fleet", "recovery", "recovery_window", "p99_ms"),
          rel_tol=0.5),
    Claim("fleet_refresh_p99", "PERF.md",
          r"/ p99 (\S+) ms at \S+ QPS \(indistinguishable",
          ("serving_fleet", "refresh", "p99_ms")),
    Claim("fleet_refresh_qps", "PERF.md",
          r"at (\S+) QPS \(indistinguishable",
          ("serving_fleet", "refresh", "qps")),
    Claim("fleet_hotkey_hit_rate", "PERF.md",
          r"\| cached \(hit rate (\S+)\)",
          ("serving_fleet", "hotkey", "cached", "cache", "hit_rate")),
    Claim("fleet_hotkey_cached_hot_p99", "PERF.md",
          r"\| cached \(hit rate \S+\) \| \S+ ms \| \S+ ms \| \S+ ms "
          r"\| (\S+) ms \|",
          ("serving_fleet", "hotkey", "cached", "hot_keys", "p99_ms")),
    Claim("fleet_hotkey_hot_p99_speedup", "PERF.md",
          r"Hot-subset p99 improves (\S+)x",
          ("serving_fleet", "hotkey", "hot_p99_speedup")),
    # PERF.md r16 + README "Instant cold start" (ISSUE 15): the
    # restart-to-first-reply comparison (artifacts off / on / on+compile
    # cache), the serving-window collapse, the artifacts-on recovery
    # window, and the pinned-artifact count against the manifest itself.
    # Cold-start totals are subprocess timings (moderate bands); the
    # serving-window and recovery numbers inherit the r15 recovery bands.
    Claim("restart_no_aot_total", "PERF.md",
          r"\| no artifacts \| (\S+) s",
          ("serving_fleet", "restart", "no_aot",
           "restart_to_first_reply_s"), rel_tol=0.25),
    Claim("restart_no_aot_window", "PERF.md",
          r"\| no artifacts \| \S+ s \| (\S+) s",
          ("serving_fleet", "restart", "no_aot",
           "rendezvous_to_first_reply_s"), rel_tol=0.5),
    Claim("restart_aot_total", "PERF.md",
          r"\| artifacts \| (\S+) s",
          ("serving_fleet", "restart", "aot",
           "restart_to_first_reply_s"), rel_tol=0.25),
    Claim("restart_aot_window", "PERF.md",
          r"\| artifacts \| \S+ s \| (\S+) s",
          ("serving_fleet", "restart", "aot",
           "rendezvous_to_first_reply_s"), rel_tol=0.5),
    Claim("restart_aot_cache_total", "PERF.md",
          r"\| artifacts \+ compile cache \| (\S+) s",
          ("serving_fleet", "restart", "aot_cache",
           "restart_to_first_reply_s"), rel_tol=0.25),
    Claim("restart_window_speedup", "PERF.md",
          r"rendezvous→first reply drops \S+ s → \S+ s \((\S+)x\)",
          ("serving_fleet", "restart", "serving_window_speedup"),
          rel_tol=0.5),
    Claim("restart_window_speedup_readme", "README.md",
          r"drops \S+ s → \S+ s \((\S+)×\)",
          ("serving_fleet", "restart", "serving_window_speedup"),
          rel_tol=0.5),
    Claim("recovery_aot_observed_s", "PERF.md",
          r"observed window (\S+) s",
          ("serving_fleet", "recovery_aot", "observed_recovery_s"),
          rel_tol=0.5),
    Claim("artifact_manifest_count", "README.md",
          r"content-hashes the (\S+) registry programs",
          lambda m: float(len(m["artifacts"])), rel_tol=0.0,
          file="tools/artifact_manifest.json"),
    Claim("comm_serve_classify", "PERF.md",
          r"Serve classify dispatch \(serve_classify_nn\) \| (\S+) B",
          ("targets", "serve_classify_nn", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_serve_topk", "PERF.md",
          r"Serve top-k lookup \(serve_topk_mf\) \| (\S+) B",
          ("targets", "serve_topk_mf", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # PERF.md r18 + README "Quantized serving" (ISSUE 17): the int8
    # dispatch wire pinned against the traced manifest (exact — a silent
    # f32 revert moves the manifest and fails jaxlint first, this table
    # second), and the committed serving_quant row's headline pair: the
    # resident-footprint reduction (deterministic byte counts, tight
    # band) and the sampled top-k overlap vs the f32 gang.
    Claim("comm_serve_topk_int8", "PERF.md",
          r"Serve top-k lookup, int8 \(serve_topk_mf_int8\) \| (\S+) B",
          ("targets", "serve_topk_mf_int8", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("quant_topk_reduction", "PERF.md",
          r"top-k table shrinks (\S+)×",
          ("serving_quant", "resident_reduction", "topk"), rel_tol=0.01),
    Claim("quant_topk_overlap", "PERF.md",
          r"mean top-10 overlap (\S+)",
          ("serving_quant", "topk_overlap", "mean"), rel_tol=0.05),
    Claim("quant_f32_qps", "PERF.md",
          r"\| f32 residents \| (\S+) \|",
          ("serving_quant", "modes", "f32", "mixes", "topk_heavy", "qps"),
          rel_tol=0.25),
    Claim("quant_int8_qps", "PERF.md",
          r"\| int8 residents \| (\S+) \|",
          ("serving_quant", "modes", "int8", "mixes", "topk_heavy",
           "qps"), rel_tol=0.25),
    Claim("quant_topk_reduction_readme", "README.md",
          r"resident\s+footprint is (\S+)× smaller",
          ("serving_quant", "resident_reduction", "topk"), rel_tol=0.01),
    Claim("quant_topk_overlap_readme", "README.md",
          r"mean top-10 overlap\s+(\S+) against the f32 gang",
          ("serving_quant", "topk_overlap", "mean"), rel_tol=0.05),
    # README "On-device resharding" + PERF.md r12 (ISSUE 11): the measured
    # CPU-mesh reshard row (the on-chip GB-scale re-measure rewrites the
    # record AND this prose, by design) plus the traced per-round byte pins
    # — the bounded-round contract: a schedule degrading toward a full
    # gather grows these exact numbers and fails jaxlint first, this table
    # second.
    Claim("reshard_seconds", "README.md",
          r"W4→W8 world change in (\S+) s",
          ("reshard", "cpu_mesh", "reshard_seconds")),
    Claim("reshard_speedup", "README.md",
          r"(\S+)× the host gather-and-resplit",
          ("reshard", "cpu_mesh", "host_vs_device_speedup")),
    Claim("reshard_perf_seconds", "PERF.md",
          r"\| device all_to_all rounds \| (\S+) s",
          ("reshard", "cpu_mesh", "reshard_seconds")),
    Claim("reshard_perf_host_seconds", "PERF.md",
          r"\| host gather-and-resplit \| (\S+) s",
          ("reshard", "cpu_mesh", "host_gather_seconds")),
    Claim("comm_reshard_a2a", "PERF.md",
          r"Reshard round \(reshard_factor_a2a\) \| (\S+) B",
          ("targets", "reshard_factor_a2a", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_reshard_ring", "PERF.md",
          r"Reshard ring schedule \(reshard_factor_ring\) \| (\S+) B",
          ("targets", "reshard_factor_ring", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_topk_rebalanced", "PERF.md",
          r"Rebalanced top-k lookup \(serve_topk_mf_rebalanced\) \| (\S+) B",
          ("targets", "serve_topk_mf_rebalanced", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # PERF.md r17 + README "Overload resilience" (ISSUE 16): the autoscale
    # ramp row. Throughput/latency/request-count inherit the wide recovery
    # bands (a time-bounded closed-loop ramp on a loaded CPU varies run to
    # run); the SHAPE claims are exact — peak/final worker count, the
    # scale-up's zero-trace AOT install (summed over whichever model moved;
    # the picked model varies with load), and the scale-down's placement
    # version. A re-measure that changes the shape must rewrite the prose.
    Claim("autoscale_requests", "PERF.md",
          r"(\S+)\s+requests answered",
          ("serving_fleet", "autoscale", "requests"), rel_tol=0.5),
    Claim("autoscale_qps", "PERF.md",
          r"(\S+) QPS at p50",
          ("serving_fleet", "autoscale", "qps"), rel_tol=0.5),
    Claim("autoscale_p50", "PERF.md",
          r"QPS at p50 (\S+) ms",
          ("serving_fleet", "autoscale", "p50_ms"), rel_tol=0.5),
    Claim("autoscale_peak", "PERF.md",
          r"\(peak (\d+), final",
          ("serving_fleet", "autoscale", "peak_workers"), rel_tol=0.0),
    Claim("autoscale_final", "PERF.md",
          r"peak \d+, final (\d+)\)",
          ("serving_fleet", "autoscale", "final_workers"), rel_tol=0.0),
    Claim("autoscale_up_traces", "PERF.md",
          r"`trace_counts = (\d+)`",
          lambda b: float(sum(b["serving_fleet"]["autoscale"]["scale_up"]
                              ["trace_counts"].values())), rel_tol=0.0),
    Claim("autoscale_up_aot_buckets", "PERF.md",
          r"`aot_loaded = (\d+)`",
          lambda b: float(sum(b["serving_fleet"]["autoscale"]["scale_up"]
                              ["aot_loaded"].values())), rel_tol=0.0),
    Claim("autoscale_prebuild_s", "PERF.md",
          r"pre-warmed offline in (\S+) s",
          ("serving_fleet", "autoscale", "prebuild_s"), rel_tol=0.5),
    Claim("autoscale_down_version", "PERF.md",
          r"driving placement\s+version (\d+)",
          ("serving_fleet", "autoscale", "scale_down", "placement_version"),
          rel_tol=0.0),
    Claim("autoscale_peak_readme", "README.md",
          r"drive workers 1 → (\d+) → 1",
          ("serving_fleet", "autoscale", "peak_workers"), rel_tol=0.0),
    # PERF.md r19 + README "Ingestion pipeline" (ISSUE 18): the streaming
    # engine's committed 1 GB row — drain rate and e2e wall quoted in both
    # docs (e2e is a full-pipeline wall on a loaded host, wider band), the
    # row's nnz/regroup wall, and the regroup schedule's per-step bytes
    # pinned against the traced manifest (exact — a regroup degrading
    # toward a full gather moves the manifest and fails jaxlint first,
    # this table second).
    Claim("ingest_drain_readme", "README.md",
          r"bounded-queue drain sustains (\S+) MB/s",
          ("ingest", "stream_load_mb_per_sec")),
    Claim("ingest_e2e_readme", "README.md",
          r"stream→assemble→fit run takes (\S+) s end to end",
          ("ingest", "e2e_stream_fit_wall_s"), rel_tol=0.25),
    Claim("ingest_drain_perf", "PERF.md",
          r"no device work\) sustains \*\*(\S+) MB/s\*\*",
          ("ingest", "stream_load_mb_per_sec")),
    Claim("ingest_e2e_perf", "PERF.md",
          r"Lloyd fit runs \*\*(\S+) s\*\* end to end",
          ("ingest", "e2e_stream_fit_wall_s"), rel_tol=0.25),
    Claim("ingest_rows", "PERF.md",
          r"part-files, (\d+) rows × 128 features",
          ("ingest", "total_rows"), rel_tol=0.0),
    Claim("ingest_overlap_eff", "PERF.md",
          r"measured\s+efficiency (\S+) here",
          ("ingest", "overlap_efficiency"), rel_tol=0.5),
    Claim("ingest_regroup_nnz", "PERF.md",
          r"committed row moves (\d+) nnz",
          ("ingest", "regroup", "nnz"), rel_tol=0.0),
    Claim("ingest_regroup_wall", "PERF.md",
          r"nnz \(8192 rows\)\s+in (\S+) s on the CPU mesh",
          ("ingest", "regroup", "wall_s"), rel_tol=0.5),
    Claim("comm_ingest_regroup", "PERF.md",
          r"Ingest COO regroup round \(ingest_coo_regroup\) \| (\S+) B",
          ("targets", "ingest_coo_regroup", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("comm_ingest_regroup_readme", "README.md",
          r"`ingest_coo_regroup` target, (\S+) B/step",
          ("targets", "ingest_coo_regroup", "bytes_per_step"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # PERF.md r20 (ISSUE 19): the static memory table — per-target
    # resident/peak/ratio rows pinned to the manifest's `memory` section
    # (jaxlint JL401 keeps the manifest honest against the traced
    # programs; these keep the PROSE honest against the manifest). Static
    # rows are exact — zero tolerance.
    Claim("mem_serve_topk_resident", "PERF.md",
          r"serve_topk_mf \(f32 dispatch\) \| (\S+) B",
          ("memory", "serve_topk_mf", "resident_arg_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_serve_topk_peak", "PERF.md",
          r"serve_topk_mf \(f32 dispatch\) \| \S+ B \| (\S+) B",
          ("memory", "serve_topk_mf", "peak_live_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_serve_topk_int8_resident", "PERF.md",
          r"serve_topk_mf_int8 \(quantized\) \| (\S+) B",
          ("memory", "serve_topk_mf_int8", "resident_arg_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_serve_topk_int8_peak", "PERF.md",
          r"serve_topk_mf_int8 \(quantized\) \| \S+ B \| (\S+) B",
          ("memory", "serve_topk_mf_int8", "peak_live_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_serve_classify_resident", "PERF.md",
          r"serve_classify_nn \| (\S+) B",
          ("memory", "serve_classify_nn", "resident_arg_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_kmeans_allreduce_peak", "PERF.md",
          r"\| kmeans_allreduce \| \S+ B \| (\S+) B",
          ("memory", "kmeans_allreduce", "peak_live_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_kmeans_int8_peak", "PERF.md",
          r"\| kmeans_allreduce_int8 \| \S+ B \| (\S+) B",
          ("memory", "kmeans_allreduce_int8", "peak_live_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_kmeans_int8_ratio", "PERF.md",
          r"\| kmeans_allreduce_int8 \| \S+ B \| \S+ B \| (\S+) \|",
          ("memory", "kmeans_allreduce_int8", "transient_peak_ratio"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_gang_rga_peak", "PERF.md",
          r"\| gang2x4_kmeans_regroupallgather \| \S+ B \| (\S+) B",
          ("memory", "gang2x4_kmeans_regroupallgather", "peak_live_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("mem_ingest_regroup_resident", "PERF.md",
          r"\| ingest_coo_regroup \| (\S+) B",
          ("memory", "ingest_coo_regroup", "resident_arg_bytes"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # PERF.md r21 (ISSUE 20): the compiled-collective table — per-target
    # post-SPMD cost rows pinned to the manifest's `hlo` section (jaxlint
    # JL502/JL504 keep the manifest honest against what the partitioner
    # emits; these keep the PROSE honest against the manifest). Compiled
    # rows are exact per jax version — zero tolerance; the op COUNTS are
    # baked into the regex literals, so a changed count goes stale-loud
    # instead of silently matching.
    Claim("hlo_kmeans_bytes", "PERF.md",
          r"\| kmeans_allreduce \| 2× all-reduce \| (\S+) B",
          ("hlo", "targets", "kmeans_allreduce", "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_kmeans_instrs", "PERF.md",
          r"\| kmeans_allreduce \| 2× all-reduce \| \S+ B \| (\d+) \|",
          ("hlo", "targets", "kmeans_allreduce", "instruction_count"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_topk_bytes", "PERF.md",
          r"\| serve_topk_mf \| 3× all-to-all \| (\S+) B",
          ("hlo", "targets", "serve_topk_mf", "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_topk_int8_bytes", "PERF.md",
          r"\| serve_topk_mf_int8 \| 3× all-to-all \| (\S+) B",
          ("hlo", "targets", "serve_topk_mf_int8",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_topk_int8_instrs", "PERF.md",
          r"\| serve_topk_mf_int8 \| 3× all-to-all \| \S+ B \| (\d+) \|",
          ("hlo", "targets", "serve_topk_mf_int8", "instruction_count"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_gang_rga_bytes", "PERF.md",
          r"\| gang2x4_kmeans_regroupallgather \| AG 65536 \+ RS 8256 "
          r"\+ AR 4 \| (\S+) B",
          ("hlo", "targets", "gang2x4_kmeans_regroupallgather",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_ingest_regroup_bytes", "PERF.md",
          r"\| ingest_coo_regroup \| 1× all-to-all \| (\S+) B",
          ("hlo", "targets", "ingest_coo_regroup",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    # the device-kind dispatch matrix rows (JL504's pins, cpu kind)
    Claim("hlo_dispatch_b8_bytes", "PERF.md",
          r"\| serve/mf/b8 \| 3× all-to-all \| (\S+) B",
          ("hlo", "device_kinds", "cpu", "serve/mf/b8",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_dispatch_b32_bytes", "PERF.md",
          r"\| serve/mf/b32 \| 3× all-to-all \| (\S+) B",
          ("hlo", "device_kinds", "cpu", "serve/mf/b32",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_dispatch_b128_bytes", "PERF.md",
          r"\| serve/mf/b128 \| 3× all-to-all \| (\S+) B",
          ("hlo", "device_kinds", "cpu", "serve/mf/b128",
           "collective_bytes_total"),
          rel_tol=0.0, file="tools/collective_budget.json"),
    Claim("hlo_dispatch_nn_b8_instrs", "PERF.md",
          r"\| serve/nn/b8 \| none \| \S+ B \| (\d+) \|",
          ("hlo", "device_kinds", "cpu", "serve/nn/b8",
           "instruction_count"),
          rel_tol=0.0, file="tools/collective_budget.json"),
]


def parse_value(text: str) -> Optional[float]:
    """'1397' → 1397.0; '1.11M' → 1.11e6; '3.05B'/'3.05G' → 3.05e9."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([KMGB])?", text)
    if not m:
        return None
    return float(m.group(1)) * _SUFFIX.get(m.group(2) or "", 1.0)


def _lookup(bench: dict, source) -> Optional[float]:
    if callable(source):
        try:
            return float(source(bench))
        except (KeyError, TypeError, ZeroDivisionError):
            return None
    node = bench
    for key in source:
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    return float(node) if isinstance(node, (int, float)) else None


def check_claim(claim: Claim, doc_text: str, bench: dict) -> Optional[str]:
    """One claim against one doc + bench record; None = consistent."""
    m = re.search(claim.pattern, doc_text)
    if not m:
        return (f"{claim.doc}: claim '{claim.claim_id}' not found — the "
                f"prose was reworded; update its entry in "
                f"tools/check_claims.py (pattern {claim.pattern!r})")
    claimed = parse_value(m.group(1))
    if claimed is None:
        return (f"{claim.doc}: claim '{claim.claim_id}' captured "
                f"{m.group(1)!r}, not a number — fix the pattern")
    recorded = _lookup(bench, claim.source)
    if recorded is None:
        return (f"{claim.doc}: claim '{claim.claim_id}' states "
                f"{m.group(1)} but the bench record has no measured value "
                f"for it (missing/null) — unmeasured rows must not be "
                f"quoted as numbers")
    if abs(claimed - recorded) > claim.rel_tol * abs(recorded):
        return (f"{claim.doc}: claim '{claim.claim_id}' states "
                f"{m.group(1)} but the committed record reads "
                f"{recorded:.4g} (> {100 * claim.rel_tol:.0f}% off) — "
                f"update the prose or re-measure")
    return None


def check(repo: str, claims: Optional[List[Claim]] = None) -> List[str]:
    records = {}
    docs = {}
    violations = []
    for claim in claims if claims is not None else CLAIMS:
        if claim.file not in records:
            with open(os.path.join(repo, claim.file)) as f:
                records[claim.file] = json.load(f)
        if claim.doc not in docs:
            with open(os.path.join(repo, claim.doc)) as f:
                docs[claim.doc] = f.read()
        v = check_claim(claim, docs[claim.doc], records[claim.file])
        if v:
            violations.append(v)
    return violations


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    repo = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = check(repo)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} claim(s) out of sync with {BENCH_FILE}")
        return 1
    print(f"all {len(CLAIMS)} headline claims within their "
          f"{BENCH_FILE} bands")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
