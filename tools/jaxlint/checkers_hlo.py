"""Lowered-HLO engine — the fifth jaxlint engine (JL5xx, ISSUE 20).

Lowers every ALREADY-traced registry target (checkers_jaxpr caches each
``make_jaxpr`` result, so this engine adds compilation only — the programs
are compiled through ``jax.jit(...).lower(...).compile()``, **never
executed**) and audits the post-SPMD optimized HLO the partitioner
actually emitted — the layer EQuARX (arXiv:2506.17615) shows decides real
wire behavior, and the layer every jaxpr-pinned contract (JL2xx bytes,
JL4xx memory) is blind to:

  JL501 inserted-collective   a compiled collective KIND
                              (``all-gather``/``all-reduce``/
                              ``collective-permute``/``all-to-all``/
                              ``reduce-scatter``) that NO traced jaxpr
                              primitive of the target maps to — GSPMD
                              added communication after tracing. The
                              finding names the op, its result shapes,
                              and the inferred insertion cause (the
                              full-broadcast / partial-sum / reshard
                              families). Real hits are fixed or
                              individually justified in the allowlist,
                              keys ``(BUDGET_FILE, target, "JL501")``.
  JL502 hlo-budget            per-target compiled cost rows (collective
                              op counts + result bytes, instruction
                              count, while-body count) pinned in the
                              ``hlo`` section of
                              ``tools/collective_budget.json``. Exact
                              equality; drift/missing/stale fail loudly
                              like JL203; regenerate deliberately with
                              ``--update-budget``. Rows are
                              jax-version-pinned (``lowered_with_jax``):
                              a different jax re-pins with ONE clear
                              finding instead of N bogus drifts.
  JL503 sharding-propagation  an operand DECLARED sharded that the
                              partitioner compiled at its GLOBAL shape —
                              the static signature of a silent full
                              replication (every device holds the whole
                              array; an all-gather usually rides the
                              wire). Allowlist-routed like JL501.
  JL504 device-kind-matrix    the 6 pinned serving dispatches
                              (``serve/{mf,nn}/b{8,32,128}`` — the
                              artifact-manifest registry) lowered on the
                              RUNNING backend and pinned per
                              ``device_kind`` (``cpu`` always in tier-1;
                              TPU kinds land when lint runs with a TPU
                              backend reachable). Pinned kinds the
                              running process cannot reach are carried
                              forward, never stale — a kind-dependent
                              lowering regression is caught before the
                              heterogeneous fleet ships.

Parsing/lowering primitives live in ``harp_tpu.aot.hlo_audit`` (shared
with the AOT store's per-artifact ``hlo`` meta rows).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tools.jaxlint.core import Finding

BUDGET_FILE = os.path.join("tools", "collective_budget.json")

# the exact-equality fields of one pinned hlo row (JL502)
HLO_FIELDS = ("collectives", "collective_bytes", "collective_bytes_total",
              "instruction_count", "while_count")

# compiled module text per (registry, target) per process — the lowering
# twin of checkers_jaxpr._TRACE_CACHE: each target compiles once no matter
# how many JL5xx passes ask for it
_HLO_CACHE: Dict[Tuple[str, str], str] = {}

# compiled module text per serving dispatch name (JL504)
_DISPATCH_CACHE: Dict[str, str] = {}


def _emit(findings: List[Finding], code: str, checker: str, target: str,
          msg: str) -> None:
    findings.append(Finding(code=code, checker=checker, path=BUDGET_FILE,
                            line=1, func=target, message=msg))


def lowered_target_text(name: str, gang: bool = False) -> str:
    """The compiled post-SPMD module text of one registry target —
    lowered from the cached trace (no re-trace, no execution)."""
    key = ("gang" if gang else "single", name)
    if key not in _HLO_CACHE:
        from harp_tpu.aot import hlo_audit
        from tools.jaxlint import checkers_jaxpr

        closed, args, _link = checkers_jaxpr.traced_target(name, gang=gang)
        _HLO_CACHE[key] = hlo_audit.compiled_text(
            hlo_audit.lower_closed(closed, args))
    return _HLO_CACHE[key]


def _jaxpr_collective_counts(closed) -> Dict[str, int]:
    from tools.jaxlint import checkers_jaxpr

    counts: Dict[str, int] = {}
    checkers_jaxpr._walk(closed.jaxpr, counts, [], {})
    return counts


# -- per-module checks (also the doctored-fixture surface for tests) --------


def inserted_findings_from(hlo_text: str, jaxpr_counts: Dict[str, int],
                           target: str) -> List[Finding]:
    """JL501 for one compiled module against its traced counts."""
    from harp_tpu.aot import hlo_audit

    findings: List[Finding] = []
    for ins in hlo_audit.inserted_collectives(hlo_text, jaxpr_counts):
        _emit(findings, "JL501", "inserted-collective", target,
              f"compiler-inserted collective: {ins.count}x {ins.op} "
              f"({ins.bytes} B, shapes {', '.join(ins.shapes)}) in the "
              f"compiled module but NO traced primitive of {target!r} "
              f"lowers to {ins.op} — the SPMD partitioner added this "
              f"communication after tracing (inferred cause: {ins.cause}); "
              f"every jaxpr-level budget is blind to it. Re-shard the "
              f"operands so the trace owns the transfer, or justify it in "
              f"the allowlist")
    return findings


def replicated_findings_from(hlo_text: str, args,
                             target: str) -> List[Finding]:
    """JL503 for one compiled module against its declared arg shardings."""
    from harp_tpu.aot import hlo_audit

    findings: List[Finding] = []
    for r in hlo_audit.replicated_where_sharded(hlo_text, args):
        gdims = ",".join(str(d) for d in r.global_shape)
        sdims = ",".join(str(d) for d in r.declared_shard)
        _emit(findings, "JL503", "sharding-propagation", target,
              f"operand {r.dtype}[{gdims}] declared sharded (per-device "
              f"block {r.dtype}[{sdims}]) but the partitioner compiled it "
              f"REPLICATED at its global shape — every device holds all "
              f"{r.nbytes} B (the static signature of a silent full "
              f"broadcast; an inserted all-gather usually rides the "
              f"wire). Fix the sharding annotation/propagation, or "
              f"justify it in the allowlist")
    return findings


def hazard_findings(name: str, gang: bool = False) -> List[Finding]:
    """JL501 + JL503 for one registry target (cached trace + lowering)."""
    from tools.jaxlint import checkers_jaxpr

    closed, args, _link = checkers_jaxpr.traced_target(name, gang=gang)
    text = lowered_target_text(name, gang=gang)
    return (inserted_findings_from(text, _jaxpr_collective_counts(closed),
                                   name)
            + replicated_findings_from(text, args, name))


# -- registry-wide rows ------------------------------------------------------


def trace_hlo_all() -> Dict[str, dict]:
    """JL502 rows for EVERY target in both registries, keyed by target
    name — compilation only, reusing the shared trace cache."""
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    from harp_tpu.aot import hlo_audit

    rows: Dict[str, dict] = {}
    for name in sorted(trace_targets.TARGETS):
        rows[name] = hlo_audit.hlo_row(lowered_target_text(name))
    for name in sorted(trace_targets.GANG_TARGETS):
        rows[name] = hlo_audit.hlo_row(lowered_target_text(name, gang=True))
    return rows


def check_hlo_hazards() -> List[Finding]:
    """JL501/JL503 over both registries (raw — the caller routes these
    through the JL5xx allowlist pool; the JL502/JL504 manifest drift is
    never suppressible)."""
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    findings: List[Finding] = []
    for name in sorted(trace_targets.TARGETS):
        findings.extend(hazard_findings(name))
    for name in sorted(trace_targets.GANG_TARGETS):
        findings.extend(hazard_findings(name, gang=True))
    return findings


# -- JL504: the serving-dispatch device-kind matrix --------------------------


def running_device_kind() -> str:
    from harp_tpu.aot.store import device_kind

    return device_kind()


def serving_dispatch_rows() -> Dict[str, dict]:
    """The 6 pinned serving dispatches (the artifact-manifest registry:
    every bucket of the deterministic ``mf``/``nn`` fleet endpoints)
    lowered on the RUNNING backend → ``{dispatch_name: hlo_row}``."""
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    from harp_tpu.aot import hlo_audit
    from harp_tpu.aot import manifest as aot_manifest
    from harp_tpu.aot import serve_artifacts
    from harp_tpu.serve import fleet as fleet_mod

    if not _DISPATCH_CACHE:
        sess = aot_manifest._session()
        for model, mspec in sorted(aot_manifest.SERVE_MODELS.items()):
            ep = fleet_mod.build_endpoint(sess, model, mspec)
            for bucket in ep.bucket_sizes:
                name = serve_artifacts.dispatch_name(model, bucket)
                _DISPATCH_CACHE[name] = hlo_audit.lower_fn_text(
                    ep.compiled(bucket), ep.dispatch_args(bucket))
    from harp_tpu.aot.hlo_audit import hlo_row

    return {name: hlo_row(text)
            for name, text in sorted(_DISPATCH_CACHE.items())}


# -- manifest (the `hlo` section) -------------------------------------------


def load_hlo_section(repo_root: str) -> Optional[dict]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f).get("hlo")


def build_hlo_section(repo_root: str) -> dict:
    """The full ``hlo`` manifest section for a regenerate: fresh rows for
    both registries, the running kind's dispatch matrix, and CARRY-FORWARD
    of every pinned device-kind matrix this process cannot re-lower (a CPU
    regenerate must not drop the TPU rows a TPU run pinned)."""
    import jax

    rows = trace_hlo_all()
    pinned = load_hlo_section(repo_root) or {}
    kinds = {k: dict(v)
             for k, v in (pinned.get("device_kinds") or {}).items()}
    kinds[running_device_kind()] = serving_dispatch_rows()
    return {
        "lowered_with_jax": jax.__version__,
        "targets": rows,
        "device_kinds": {k: kinds[k] for k in sorted(kinds)},
    }


def _diff_row(traced: dict, pinned: dict) -> List[str]:
    drift = []
    for field in HLO_FIELDS:
        got, want = traced.get(field), pinned.get(field)
        if got != want:
            drift.append(f"{field}: lowered {got} vs pinned {want}")
    return drift


def check_hlo_budget(repo_root: str,
                     rows: Optional[Dict[str, dict]] = None,
                     kind_rows: Optional[Dict[str, dict]] = None,
                     ) -> List[Finding]:
    """JL502 (per-target compiled rows) + JL504 (device-kind dispatch
    matrix) vs the manifest's ``hlo`` section — exact equality,
    stale/missing loud, env mismatch ONE re-pin finding."""
    import jax

    findings: List[Finding] = []
    pinned = load_hlo_section(repo_root)
    if pinned is None:
        _emit(findings, "JL502", "hlo-budget", "<manifest>",
              f"{BUDGET_FILE} has no hlo section — the compiled-collective "
              f"contract is unpinned; regenerate with `python -m "
              f"tools.jaxlint --update-budget` and commit the hlo rows")
        return findings
    pinned_jax = pinned.get("lowered_with_jax")
    if pinned_jax != jax.__version__:
        # compiled instruction counts are only deterministic per jax/XLA
        # version — N bogus drifts would bury the one real message
        _emit(findings, "JL502", "hlo-budget", "<manifest>",
              f"hlo section was lowered with jax {pinned_jax!r} but this "
              f"process runs {jax.__version__!r} — compiled rows are "
              f"version-specific; re-pin with --update-budget on the CI "
              f"environment")
        return findings
    if rows is None:
        rows = trace_hlo_all()
    pinned_rows = pinned.get("targets", {})
    for name, row in sorted(rows.items()):
        if name not in pinned_rows:
            _emit(findings, "JL502", "hlo-budget", name,
                  f"lowered target {name!r} has no hlo row — run "
                  f"--update-budget and review the new row")
            continue
        drift = _diff_row(row, pinned_rows[name])
        if drift:
            _emit(findings, "JL502", "hlo-budget", name,
                  f"compiled-HLO budget drift ({'; '.join(drift)}) — what "
                  f"the PARTITIONER emits for this program moved (a grown "
                  f"collective row is wire traffic the jaxpr budget never "
                  f"saw; a grown instruction/while count is a compiled "
                  f"program change); if intentional, --update-budget and "
                  f"review the diff")
    for name in sorted(set(pinned_rows) - set(rows)):
        _emit(findings, "JL502", "hlo-budget", name,
              f"hlo row {name!r} matches no trace target — stale row "
              f"(target renamed/removed); regenerate with --update-budget")

    # JL504: the running kind's dispatch matrix. Pinned kinds this
    # process cannot reach (the TPU rows, from a CPU session) are
    # CARRIED FORWARD — skipped here, preserved by build_hlo_section.
    if kind_rows is None:
        kind_rows = serving_dispatch_rows()
    kind = running_device_kind()
    pinned_kinds = pinned.get("device_kinds", {})
    if kind not in pinned_kinds:
        _emit(findings, "JL504", "device-kind-matrix", f"<{kind}>",
              f"no pinned serving-dispatch row matrix for the running "
              f"device kind {kind!r} ({len(kind_rows)} dispatches lower) "
              f"— run --update-budget on this backend and commit the "
              f"matrix")
        return findings
    pinned_matrix = pinned_kinds[kind]
    for name, row in sorted(kind_rows.items()):
        if name not in pinned_matrix:
            _emit(findings, "JL504", "device-kind-matrix", name,
                  f"serving dispatch {name!r} has no pinned hlo row under "
                  f"device kind {kind!r} — run --update-budget and review "
                  f"the new row")
            continue
        drift = _diff_row(row, pinned_matrix[name])
        if drift:
            _emit(findings, "JL504", "device-kind-matrix", name,
                  f"serving dispatch {name!r} lowers differently on "
                  f"device kind {kind!r} than pinned "
                  f"({'; '.join(drift)}) — a kind-dependent lowering "
                  f"regression (the heterogeneous fleet would ship it "
                  f"blind); if intentional, --update-budget and review "
                  f"the diff")
    for name in sorted(set(pinned_matrix) - set(kind_rows)):
        _emit(findings, "JL504", "device-kind-matrix", name,
              f"pinned dispatch row {name!r} under device kind {kind!r} "
              f"matches no serving dispatch — stale row; regenerate with "
              f"--update-budget")
    return findings
