"""jaxpr checkers — the traced half of jaxlint.

Codes:
  JL201 collective-budget   traced collective counts/kinds for a model step
                            program drifted from the committed manifest
                            ``tools/collective_budget.json`` (regenerate
                            deliberately with ``--update-budget`` — the diff
                            IS the review surface, exactly like check_claims
                            pins bench numbers).
  JL202 dtype-policy        a traced program binds a float64/complex128
                            value (tier-1 runs x64-disabled; an f64 that
                            appears under x64 would double every collective
                            payload), or runs a bf16×bf16 dot_general that
                            ACCUMULATES in bf16 — the repo-wide policy
                            (ops/lane_pack's exactness contract) is bf16
                            operands with f32 accumulation
                            (preferred_element_type), never bf16 sums.

Everything here uses ``jax.make_jaxpr`` only: programs are traced, never
executed, so the whole budget check runs in tier-1 on the virtual CPU mesh.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tools.jaxlint.core import Finding

BUDGET_FILE = os.path.join("tools", "collective_budget.json")

# jaxpr primitive names that move bytes across the worker axis. axis_index
# is deliberately excluded: it reads the device grid, it does not
# communicate, so it is not part of the budget contract.
COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "all_gather", "all_to_all", "reduce_scatter",
    "psum_scatter", "ppermute", "pshuffle", "pbroadcast", "pgather",
}


def _subjaxprs(eqn):
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def _walk(jaxpr, counts: Dict[str, int], dtype_bad: List[str]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            counts[name] = counts.get(name, 0) + 1
        # dtype policy: no f64/c128 anywhere; bf16 dots must accumulate f32
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in ("float64", "complex128"):
                dtype_bad.append(f"{name} binds a {dt} value")
        if name == "dot_general":
            in_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                      for v in eqn.invars]
            out_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                       for v in eqn.outvars]
            if (in_dts and all(d == "bfloat16" for d in in_dts)
                    and all(d == "bfloat16" for d in out_dts)):
                dtype_bad.append(
                    "bf16 x bf16 dot_general accumulating in bf16 — pass "
                    "preferred_element_type=jnp.float32 (lane_pack "
                    "exactness contract: bf16 operands, f32 sums)")
        for sub in _subjaxprs(eqn):
            _walk(sub, counts, dtype_bad)


def trace_target(name: str) -> Tuple[Dict[str, int], List[str]]:
    """Trace one registry target; returns (collective counts, dtype issues).

    Counts are STATIC occurrences in the traced program. The hot loop of
    every target is a ``lax.scan`` over iterations, so a collective in the
    scan body counts once — i.e. the manifest records collectives **per
    step**, not per run (iteration counts are config, not contract).
    """
    import jax

    from tools.jaxlint import trace_targets

    fn, args = trace_targets.TARGETS[name]()
    closed = jax.make_jaxpr(fn)(*args)
    counts: Dict[str, int] = {}
    dtype_bad: List[str] = []
    _walk(closed.jaxpr, counts, dtype_bad)
    return counts, dtype_bad


def trace_all() -> Dict[str, Tuple[Dict[str, int], List[str]]]:
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    return {name: trace_target(name)
            for name in sorted(trace_targets.TARGETS)}


def load_budget(repo_root: str) -> Optional[dict]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budget(repo_root: str,
                 traced: Dict[str, Tuple[Dict[str, int], List[str]]]) -> str:
    import jax

    path = os.path.join(repo_root, BUDGET_FILE)
    doc = {
        "_contract": (
            "Collectives-per-step manifest: static collective-primitive "
            "counts in each model's traced step program at tier-1 shapes "
            "(tools/jaxlint/trace_targets.py). Tier-1 fails on ANY drift — "
            "an extra psum per step is a perf regression, a changed kind "
            "is a changed comm algorithm; regenerate deliberately with "
            "`python -m tools.jaxlint --update-budget` and review the "
            "diff. Counts are per STEP (scan bodies count once)."),
        "traced_with_jax": jax.__version__,
        "targets": {name: {"collectives": dict(sorted(counts.items()))}
                    for name, (counts, _bad) in sorted(traced.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return path


def check_budget(repo_root: str,
                 traced: Optional[Dict[str, Tuple[Dict[str, int],
                                                  List[str]]]] = None,
                 ) -> List[Finding]:
    """JL201/JL202 findings for the whole trace registry."""
    if traced is None:
        traced = trace_all()
    findings: List[Finding] = []

    def emit(code, checker, target, msg):
        findings.append(Finding(
            code=code, checker=checker, path=BUDGET_FILE, line=1,
            func=target, message=msg))

    budget = load_budget(repo_root)
    if budget is None:
        emit("JL201", "collective-budget", "<manifest>",
             f"{BUDGET_FILE} is missing — generate it with "
             f"`python -m tools.jaxlint --update-budget` and commit it")
        budget_targets = {}
    else:
        budget_targets = budget.get("targets", {})

    for name, (counts, dtype_bad) in sorted(traced.items()):
        for issue in dtype_bad:
            emit("JL202", "dtype-policy", name, issue)
        if budget is None:
            continue
        if name not in budget_targets:
            emit("JL201", "collective-budget", name,
                 f"traced target {name!r} has no manifest entry — run "
                 f"--update-budget and review the new row")
            continue
        pinned = budget_targets[name].get("collectives", {})
        if dict(counts) != dict(pinned):
            drift = []
            for kind in sorted(set(counts) | set(pinned)):
                got, want = counts.get(kind, 0), pinned.get(kind, 0)
                if got != want:
                    drift.append(f"{kind}: traced {got} vs pinned {want}")
            emit("JL201", "collective-budget", name,
                 f"collective budget drift ({'; '.join(drift)}) — if "
                 f"intentional, regenerate with --update-budget and review "
                 f"the diff; if not, a step gained/lost communication")
    for name in sorted(set(budget_targets) - set(traced)):
        emit("JL201", "collective-budget", name,
             f"manifest entry {name!r} matches no trace target — stale row "
             f"(target renamed/removed); regenerate with --update-budget")
    return findings
