"""jaxpr checkers — the traced half of jaxlint.

Codes:
  JL201 collective-budget   traced collective counts/kinds for a model step
                            program drifted from the committed manifest
                            ``tools/collective_budget.json`` (regenerate
                            deliberately with ``--update-budget`` — the diff
                            IS the review surface, exactly like check_claims
                            pins bench numbers).
  JL202 dtype-policy        a traced program binds a float64/complex128
                            value (tier-1 runs x64-disabled; an f64 that
                            appears under x64 would double every collective
                            payload), or runs a bf16×bf16 dot_general that
                            ACCUMULATES in bf16 — the repo-wide policy
                            (ops/lane_pack's exactness contract) is bf16
                            operands with f32 accumulation
                            (preferred_element_type), never bf16 sums.
  JL203 byte-budget         traced collective OPERAND BYTES per step drifted
                            from the manifest's ``bytes_per_step`` /
                            ``bytes_by_kind``. Counts alone miss comm-VOLUME
                            regressions: the same one ppermute per hop can
                            silently grow 4x when a quantized path falls
                            back to f32 (the dtype changes, the count does
                            not) or when an operand shape balloons. Bytes
                            are summed over the collective equations'
                            operand avals at tier-1 shapes — per STEP, same
                            scan-body-counts-once convention as JL201.

Everything here uses ``jax.make_jaxpr`` only: programs are traced, never
executed, so the whole budget check runs in tier-1 on the virtual CPU mesh.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Dict, List, Optional, Tuple

from tools.jaxlint.core import Finding

BUDGET_FILE = os.path.join("tools", "collective_budget.json")

# jaxpr primitive names that move bytes across the worker axis. axis_index
# is deliberately excluded: it reads the device grid, it does not
# communicate, so it is not part of the budget contract.
COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "all_gather", "all_to_all", "reduce_scatter",
    "psum_scatter", "ppermute", "pshuffle", "pbroadcast", "pgather",
}

# Fused ring-DMA hops (r10): on TPU these are in-kernel
# `make_async_remote_copy`s with NO collective primitive in the jaxpr; on
# the CPU tracing mesh the engine lowers them through a jit tagged with
# this name (must equal harp_tpu.ops.ring_dma.FUSED_HOP_NAME — tier-1
# asserts the two constants agree). The walker books a tagged call's
# operand bytes as the synthetic kind "fused_dma" and does NOT recurse into
# it — the inner ppermute is the transport the tag REPLACES, so counting
# both would double-charge, and counting only the ppermute would let a
# silent revert to a bare permute keep the same byte row. The manifest pins
# the kind per target (plus the explicit `fused_dma_bytes_per_step` field),
# so a fused schedule quietly degrading to ppermute moves bytes BETWEEN
# kinds and fails JL201/JL203.
FUSED_HOP_PREFIX = "ring_dma_fused_hop"


def _subjaxprs(eqn):
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    return n * dtype.itemsize


def _walk(jaxpr, counts: Dict[str, int], dtype_bad: List[str],
          nbytes: Dict[str, int]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if (name == "pjit"
                and str(eqn.params.get("name", "")).startswith(
                    FUSED_HOP_PREFIX)):
            counts["fused_dma"] = counts.get("fused_dma", 0) + 1
            nbytes["fused_dma"] = nbytes.get("fused_dma", 0) + sum(
                _aval_bytes(v) for v in eqn.invars)
            continue     # no recursion: the tag REPLACES the inner permute
        if name in COLLECTIVE_PRIMS:
            counts[name] = counts.get(name, 0) + 1
            # operand bytes = what the collective puts on the wire at tier-1
            # shapes (per-worker, inside shard_map). Summed over invars so a
            # multi-operand psum charges every payload.
            nbytes[name] = nbytes.get(name, 0) + sum(
                _aval_bytes(v) for v in eqn.invars)
        # dtype policy: no f64/c128 anywhere; bf16 dots must accumulate f32
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in ("float64", "complex128"):
                dtype_bad.append(f"{name} binds a {dt} value")
        if name == "dot_general":
            in_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                      for v in eqn.invars]
            out_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                       for v in eqn.outvars]
            if (in_dts and all(d == "bfloat16" for d in in_dts)
                    and all(d == "bfloat16" for d in out_dts)):
                dtype_bad.append(
                    "bf16 x bf16 dot_general accumulating in bf16 — pass "
                    "preferred_element_type=jnp.float32 (lane_pack "
                    "exactness contract: bf16 operands, f32 sums)")
        for sub in _subjaxprs(eqn):
            _walk(sub, counts, dtype_bad, nbytes)


# One make_jaxpr per (registry, target) per process: the collective
# engines, the gang engine, AND the memory engine (checkers_memory, ISSUE
# 19) all analyze the same traced program, so the trace itself is cached —
# the memory pass costs no extra tracing when it follows a budget pass.
# Values are (ClosedJaxpr, placed args, workers-axis link class at trace
# time); tier-1 shapes keep the held arrays tiny.
_TRACE_CACHE: Dict[Tuple[str, str], tuple] = {}


def traced_target(name: str, gang: bool = False) -> tuple:
    """The cached ``(closed_jaxpr, args, link_class)`` of one registry
    target, tracing it on first use (gang targets trace under the DCN
    hint — see :func:`trace_gang_target`)."""
    key = ("gang" if gang else "single", name)
    if key not in _TRACE_CACHE:
        import jax

        from tools.jaxlint import trace_targets

        if gang:
            from harp_tpu.parallel import mesh as mesh_lib

            with _gang_link_hint("dcn"):
                fn, args = trace_targets.GANG_TARGETS[name]()
                closed = jax.make_jaxpr(fn)(*args)
                link = mesh_lib.axis_link_class(mesh_lib.WORKERS)
        else:
            fn, args = trace_targets.TARGETS[name]()
            closed = jax.make_jaxpr(fn)(*args)
            link = None
        _TRACE_CACHE[key] = (closed, args, link)
    return _TRACE_CACHE[key]


def trace_target(name: str) -> Tuple[Dict[str, int], List[str],
                                     Dict[str, int]]:
    """Trace one registry target; returns (collective counts, dtype issues,
    collective operand bytes by kind).

    Counts/bytes are STATIC occurrences in the traced program. The hot loop
    of every target is a ``lax.scan`` over iterations, so a collective in
    the scan body counts once — i.e. the manifest records collectives **per
    step**, not per run (iteration counts are config, not contract).
    """
    closed, _args, _link = traced_target(name)
    counts: Dict[str, int] = {}
    dtype_bad: List[str] = []
    nbytes: Dict[str, int] = {}
    _walk(closed.jaxpr, counts, dtype_bad, nbytes)
    return counts, dtype_bad, nbytes


def trace_all() -> Dict[str, Tuple[Dict[str, int], List[str],
                                   Dict[str, int]]]:
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    return {name: trace_target(name)
            for name in sorted(trace_targets.TARGETS)}


# --------------------------------------------------------------------------
# gang mode (ISSUE 13): per-process shard shapes + DCN/ICI byte split
# --------------------------------------------------------------------------
#
# Wire model for the link split (EQuARX-style accounting, arXiv:2506.17615,
# applied to the DCN/ICI boundary that DrJAX-style multi-mesh programs make
# first-class, arXiv:2403.07128). The gang lays the workers axis out
# contiguously per process (make_mesh over distributed.initialize's device
# order — mp_smoke's layout), so on the W-worker ring exactly P of the W
# hop edges cross a process (= host = DCN) boundary:
#
# * ring-scheduled kinds (ppermute and the pshuffle permutation, the fused
#   ring-DMA hops, and the reduction/gather family XLA lowers to ring
#   schedules on a 1-D axis): DCN share = P / W of the operand bytes.
# * all_to_all: every worker exchanges with W-1 peers, of which W - D sit
#   on other hosts: DCN share = (W - D) / (W - 1).
#
# Shares are integer floor (DCN rounds down, ICI takes the remainder), so
# the split is deterministic and sums exactly to bytes_by_kind. The split
# only applies when the workers axis is hinted "dcn"
# (mesh.set_axis_link_class — gang launchers do this at bootstrap; a
# single-pod gang's hint stays "ici" and every byte books as ICI).

_ALL_TO_ALL_KINDS = {"all_to_all"}     # pshuffle is a permutation — ring
#                                        model, like ppermute


def split_bytes_by_link(nbytes: Dict[str, int], *, world: int,
                        processes: int, devices_per_process: int,
                        link_class: str) -> Dict[str, Dict[str, int]]:
    """``bytes_by_kind`` split into ``{"dcn": {...}, "ici": {...}}``."""
    dcn: Dict[str, int] = {}
    ici: Dict[str, int] = {}
    for kind, b in sorted(nbytes.items()):
        if link_class != "dcn" or processes <= 1 or world <= 1:
            num, den = 0, 1
        elif kind in _ALL_TO_ALL_KINDS:
            num, den = world - devices_per_process, world - 1
        else:
            num, den = processes, world
        d = b * num // den
        dcn[kind] = d
        ici[kind] = b - d
    return {"dcn": dcn, "ici": ici}


def per_process_shard_shapes(args, devices_per_process: int) -> List[list]:
    """The per-PROCESS block shape of every traced program input.

    A replicated dim keeps its global extent; a dim sharded over the
    workers axis scales the per-device shard by the process's local device
    count. This is the layout each host actually materializes — the
    resharding contract the fleet item moves against (arXiv:2112.01075)."""
    import jax

    shapes: List[list] = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        shape = tuple(int(s) for s in shape)
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            shapes.append(list(shape))        # host array: fully replicated
            continue
        try:
            shard = tuple(int(s) for s in sharding.shard_shape(shape))
        except (TypeError, ValueError):
            shapes.append(list(shape))
            continue
        shapes.append([g if s == g else min(g, s * devices_per_process)
                       for g, s in zip(shape, shard)])
    return shapes


@contextlib.contextmanager
def _gang_link_hint(link_class: str):
    """Hint the workers axis for the duration of one gang trace, restoring
    the ambient hint after (the hint is process-global mesh state)."""
    from harp_tpu.parallel import mesh as mesh_lib

    prev = mesh_lib.axis_link_class(mesh_lib.WORKERS)
    mesh_lib.set_axis_link_class(mesh_lib.WORKERS, link_class)
    try:
        yield
    finally:
        mesh_lib.set_axis_link_class(mesh_lib.WORKERS, prev)


def trace_gang_target(name: str) -> dict:
    """Trace one gang-mode target under the DCN hint; returns the full
    manifest-row dict (counts, dtype issues, bytes, shard shapes, link
    split).

    The DCN hint is live DURING tracing, so link-aware code paths (the
    rotation pipeline's DCN chunking) trace their actual cross-pod
    program — the gang row pins the program a real 2-host gang runs, not
    the single-pod one retitled.
    """
    from tools.jaxlint import trace_targets

    P = trace_targets.GANG_PROCESSES
    D = trace_targets.GANG_DEVICES_PER_PROCESS
    closed, args, link = traced_target(name, gang=True)
    counts: Dict[str, int] = {}
    dtype_bad: List[str] = []
    nbytes: Dict[str, int] = {}
    _walk(closed.jaxpr, counts, dtype_bad, nbytes)
    by_link = split_bytes_by_link(
        nbytes, world=trace_targets.NUM_WORKERS, processes=P,
        devices_per_process=D, link_class=link)
    shard_shapes = per_process_shard_shapes(args, D)
    return {
        "processes": P,
        "devices_per_process": D,
        "collectives": dict(sorted(counts.items())),
        "per_process_shard_shapes": shard_shapes,
        "bytes_per_step": sum(nbytes.values()),
        "bytes_by_kind": dict(sorted(nbytes.items())),
        "bytes_by_link": by_link,
        "dcn_bytes_per_step": sum(by_link["dcn"].values()),
        "_dtype_bad": dtype_bad,     # stripped before the manifest write
    }


def trace_gang_all() -> Dict[str, dict]:
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    return {name: trace_gang_target(name)
            for name in sorted(trace_targets.GANG_TARGETS)}


def load_budget(repo_root: str) -> Optional[dict]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budget(repo_root: str, traced, gang=None, memory=None,
                 hlo=None) -> str:
    """Rewrite the manifest from ``traced`` (and ``gang``, the gang-mode
    rows from :func:`trace_gang_all`; ``memory``, the static memory rows
    from ``checkers_memory.trace_memory_all``; ``hlo``, the compiled-HLO
    section from ``checkers_hlo.build_hlo_section``. None carries the
    committed rows of that section forward unchanged so a single-engine
    regenerate can't silently drop another engine's contract)."""
    import jax

    if gang is None:
        existing = load_budget(repo_root) or {}
        gang_rows = existing.get("gang_targets", {})
    else:
        gang_rows = {name: {k: v for k, v in row.items()
                            if not k.startswith("_")}
                     for name, row in sorted(gang.items())}
    if memory is None:
        existing = load_budget(repo_root) or {}
        memory_rows = existing.get("memory", {})
    else:
        memory_rows = {name: dict(row)
                       for name, row in sorted(memory.items())}
    if hlo is None:
        existing = load_budget(repo_root) or {}
        hlo_section = existing.get("hlo", {})
    else:
        hlo_section = dict(hlo)
    path = os.path.join(repo_root, BUDGET_FILE)
    doc = {
        "_contract": (
            "Collectives-per-step manifest: static collective-primitive "
            "counts AND operand bytes in each model's traced step program "
            "at tier-1 shapes (tools/jaxlint/trace_targets.py). Tier-1 "
            "fails on ANY drift — an extra psum per step is a perf "
            "regression, a changed kind is a changed comm algorithm, and "
            "changed bytes at the same counts is a comm-VOLUME regression "
            "(e.g. a quantized path silently falling back to f32); "
            "regenerate deliberately with `python -m tools.jaxlint "
            "--update-budget` and review the diff. Counts/bytes are per "
            "STEP (scan bodies count once). fused_dma_bytes_per_step pins "
            "the bytes that move via in-kernel ring DMA "
            "(ops/ring_dma fused hops — tagged jits on the tracing mesh): "
            "a fused schedule silently reverting to bare ppermute moves "
            "these bytes between kinds and fails the gate. gang_targets "
            "pin the dryrun_multichip GANG-MODE step programs: the same "
            "step traced under the declared processes x devices_per_process "
            "topology with the workers axis hinted DCN — each row adds "
            "per_process_shard_shapes (what every HOST holds; drift is a "
            "partitioning-contract break, JL201) and bytes_by_link "
            "(bytes_by_kind split DCN vs ICI by the ring-edge/peer model "
            "in checkers_jaxpr.split_bytes_by_link; grown DCN bytes at "
            "fixed counts is the cross-pod regression single-process rows "
            "cannot see, JL203). memory pins the STATIC memory rows "
            "(ISSUE 19, checkers_memory/static_memory): resident_arg_bytes "
            "(input + closed-over-constant footprint), peak_live_bytes "
            "(liveness peak over the traced program, sub-jaxprs "
            "recursively), and transient_peak_ratio (peak/resident, "
            "rounded) per target across BOTH registries — a grown peak is "
            "a memory regression that otherwise ships invisibly until an "
            "OOM on real HBM, and the resident rows are the model mall's "
            "planning input (JL401). hlo pins the POST-SPMD compiled "
            "contract (ISSUE 20, checkers_hlo/hlo_audit): every target "
            "lowered through jax.jit(...).lower().compile() — compilation "
            "only, never execution — with per-target compiler-emitted "
            "collective counts + result-shape bytes, instruction count, "
            "and while-body count (JL502; the layer GSPMD is free to "
            "rewrite AFTER tracing, so a jaxpr-clean program can still "
            "grow wire traffic only this section sees), plus "
            "device_kinds: the 6 pinned serving dispatches lowered per "
            "reachable device kind (JL504 — cpu always; TPU kinds pin "
            "when lint runs there, and sessions that cannot reach a "
            "pinned kind carry its matrix forward, never stale). Rows "
            "are exact per lowered_with_jax version; a different jax "
            "re-pins with ONE finding."),
        "traced_with_jax": jax.__version__,
        "targets": {
            name: {
                "collectives": dict(sorted(counts.items())),
                "bytes_per_step": sum(nbytes.values()),
                "bytes_by_kind": dict(sorted(nbytes.items())),
                "fused_dma_bytes_per_step": nbytes.get("fused_dma", 0),
            }
            for name, (counts, _bad, nbytes) in sorted(traced.items())},
        "gang_targets": gang_rows,
        "memory": memory_rows,
        "hlo": hlo_section,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return path


def check_budget(repo_root: str, traced=None) -> List[Finding]:
    """JL201/JL202/JL203 findings for the whole trace registry."""
    if traced is None:
        traced = trace_all()
    findings: List[Finding] = []

    def emit(code, checker, target, msg):
        findings.append(Finding(
            code=code, checker=checker, path=BUDGET_FILE, line=1,
            func=target, message=msg))

    budget = load_budget(repo_root)
    if budget is None:
        emit("JL201", "collective-budget", "<manifest>",
             f"{BUDGET_FILE} is missing — generate it with "
             f"`python -m tools.jaxlint --update-budget` and commit it")
        budget_targets = {}
    else:
        budget_targets = budget.get("targets", {})

    for name, (counts, dtype_bad, nbytes) in sorted(traced.items()):
        for issue in dtype_bad:
            emit("JL202", "dtype-policy", name, issue)
        if budget is None:
            continue
        if name not in budget_targets:
            emit("JL201", "collective-budget", name,
                 f"traced target {name!r} has no manifest entry — run "
                 f"--update-budget and review the new row")
            continue
        pinned = budget_targets[name].get("collectives", {})
        if dict(counts) != dict(pinned):
            drift = []
            for kind in sorted(set(counts) | set(pinned)):
                got, want = counts.get(kind, 0), pinned.get(kind, 0)
                if got != want:
                    drift.append(f"{kind}: traced {got} vs pinned {want}")
            emit("JL201", "collective-budget", name,
                 f"collective budget drift ({'; '.join(drift)}) — if "
                 f"intentional, regenerate with --update-budget and review "
                 f"the diff; if not, a step gained/lost communication")
        # JL203: comm volume. A manifest row predating byte budgets (no
        # bytes_per_step key) is itself a finding — the byte contract must
        # cover every target.
        pinned_total = budget_targets[name].get("bytes_per_step")
        pinned_kinds = budget_targets[name].get("bytes_by_kind", {})
        total = sum(nbytes.values())
        if pinned_total is None:
            emit("JL203", "byte-budget", name,
                 f"manifest entry {name!r} has no bytes_per_step — "
                 f"regenerate with --update-budget so the byte contract "
                 f"covers it")
        elif total != pinned_total or dict(nbytes) != dict(pinned_kinds):
            drift = []
            for kind in sorted(set(nbytes) | set(pinned_kinds)):
                got, want = nbytes.get(kind, 0), pinned_kinds.get(kind, 0)
                if got != want:
                    drift.append(f"{kind}: traced {got} B vs pinned {want} B")
            if total != pinned_total:
                drift.append(f"total: traced {total} B vs pinned "
                             f"{pinned_total} B")
            emit("JL203", "byte-budget", name,
                 f"collective byte-budget drift ({'; '.join(drift)}) — "
                 f"comm VOLUME changed at tier-1 shapes (same-count dtype "
                 f"widening, e.g. a quantized path silently reverting to "
                 f"f32, lands here); if intentional, --update-budget and "
                 f"review the diff")
        # fused ring-DMA contract: the explicit fused_dma_bytes_per_step
        # row must exist for any target whose trace moves bytes via the
        # fused engine, and must agree with the by-kind row (a fused target
        # silently reverting to ppermute already failed the kind drift
        # above — fused_dma bytes collapse to 0 and ppermute grows).
        traced_fused = nbytes.get("fused_dma", 0)
        pinned_fused = budget_targets[name].get("fused_dma_bytes_per_step")
        if traced_fused and pinned_fused is None:
            emit("JL203", "byte-budget", name,
                 f"target {name!r} moves {traced_fused} B/step via fused "
                 f"ring DMA but the manifest row has no "
                 f"fused_dma_bytes_per_step — regenerate with "
                 f"--update-budget so the fused contract covers it")
        elif (pinned_fused is not None
              and pinned_fused != pinned_kinds.get("fused_dma", 0)):
            emit("JL203", "byte-budget", name,
                 f"manifest inconsistency for {name!r}: "
                 f"fused_dma_bytes_per_step={pinned_fused} disagrees with "
                 f"bytes_by_kind fused_dma="
                 f"{pinned_kinds.get('fused_dma', 0)} — hand-edited row? "
                 f"regenerate with --update-budget")
    for name in sorted(set(budget_targets) - set(traced)):
        emit("JL201", "collective-budget", name,
             f"manifest entry {name!r} matches no trace target — stale row "
             f"(target renamed/removed); regenerate with --update-budget")
    return findings


def check_gang_budget(repo_root: str, gang=None) -> List[Finding]:
    """JL201/JL202/JL203 for the gang-mode rows (module docstring: the
    gang split of counts, per-process shard shapes, and DCN/ICI bytes)."""
    if gang is None:
        gang = trace_gang_all()
    findings: List[Finding] = []

    def emit(code, checker, target, msg):
        findings.append(Finding(
            code=code, checker=checker, path=BUDGET_FILE, line=1,
            func=target, message=msg))

    budget = load_budget(repo_root)
    pinned_rows = (budget or {}).get("gang_targets", {})
    if budget is not None and not pinned_rows and gang:
        emit("JL201", "gang-budget", "<manifest>",
             f"{BUDGET_FILE} has no gang_targets section but "
             f"{len(gang)} gang-mode targets trace — regenerate with "
             f"`python -m tools.jaxlint --update-budget` and commit the "
             f"gang rows")
    for name, row in sorted(gang.items()):
        for issue in row.get("_dtype_bad", []):
            emit("JL202", "dtype-policy", name, issue)
        if budget is None or name not in pinned_rows:
            if budget is not None and pinned_rows:
                emit("JL201", "gang-budget", name,
                     f"gang-mode target {name!r} has no manifest row — "
                     f"run --update-budget and review the new row")
            continue
        pinned = pinned_rows[name]
        # topology + counts + per-process shard shapes: JL201 (a changed
        # shard shape means each host holds a different block — the
        # partitioning contract moved, not just its cost)
        for key, label in (("processes", "process count"),
                           ("devices_per_process", "devices per process"),
                           ("collectives", "collective counts"),
                           ("per_process_shard_shapes",
                            "per-process shard shapes")):
            if row.get(key) != pinned.get(key):
                emit("JL201", "gang-budget", name,
                     f"gang-mode {label} drift: traced {row.get(key)} vs "
                     f"pinned {pinned.get(key)} — if intentional, "
                     f"regenerate with --update-budget and review the "
                     f"diff; if not, the gang step program (or its "
                     f"per-host partitioning) changed")
        # bytes: JL203, with the DCN split called out separately — DCN is
        # the scarce link, so its growth is the headline even when totals
        # barely move
        traced_link = row.get("bytes_by_link", {})
        pinned_link = pinned.get("bytes_by_link", {})
        if pinned.get("bytes_per_step") is None:
            emit("JL203", "gang-budget", name,
                 f"gang manifest row {name!r} has no bytes_per_step — "
                 f"regenerate with --update-budget so the gang byte "
                 f"contract covers it")
        elif (row.get("bytes_per_step") != pinned.get("bytes_per_step")
              or row.get("bytes_by_kind") != pinned.get("bytes_by_kind")
              or traced_link != pinned_link):
            drift = []
            for link in ("dcn", "ici"):
                got_k = traced_link.get(link, {})
                want_k = pinned_link.get(link, {})
                for kind in sorted(set(got_k) | set(want_k)):
                    g, w = got_k.get(kind, 0), want_k.get(kind, 0)
                    if g != w:
                        drift.append(f"{link}/{kind}: traced {g} B vs "
                                     f"pinned {w} B")
            if row.get("bytes_per_step") != pinned.get("bytes_per_step"):
                drift.append(f"total: traced {row.get('bytes_per_step')} B "
                             f"vs pinned {pinned.get('bytes_per_step')} B")
            dcn_got = row.get("dcn_bytes_per_step", 0)
            dcn_want = pinned.get("dcn_bytes_per_step", 0)
            headline = (f"DCN bytes {dcn_got} vs pinned {dcn_want} — "
                        if dcn_got != dcn_want else "")
            emit("JL203", "gang-budget", name,
                 f"gang-mode byte-budget drift ({headline}"
                 f"{'; '.join(drift) or 'kind-level split moved'}) — "
                 f"cross-pod comm volume changed at tier-1 shapes; if "
                 f"intentional, --update-budget and review the diff")
        elif (pinned.get("dcn_bytes_per_step") is not None
              and pinned["dcn_bytes_per_step"]
              != sum(pinned_link.get("dcn", {}).values())):
            emit("JL203", "gang-budget", name,
                 f"gang manifest inconsistency for {name!r}: "
                 f"dcn_bytes_per_step={pinned['dcn_bytes_per_step']} "
                 f"disagrees with its bytes_by_link dcn sum — hand-edited "
                 f"row? regenerate with --update-budget")
    for name in sorted(set(pinned_rows) - set(gang)):
        emit("JL201", "gang-budget", name,
             f"gang manifest row {name!r} matches no gang-mode trace "
             f"target — stale row; regenerate with --update-budget")
    return findings
