"""jaxpr checkers — the traced half of jaxlint.

Codes:
  JL201 collective-budget   traced collective counts/kinds for a model step
                            program drifted from the committed manifest
                            ``tools/collective_budget.json`` (regenerate
                            deliberately with ``--update-budget`` — the diff
                            IS the review surface, exactly like check_claims
                            pins bench numbers).
  JL202 dtype-policy        a traced program binds a float64/complex128
                            value (tier-1 runs x64-disabled; an f64 that
                            appears under x64 would double every collective
                            payload), or runs a bf16×bf16 dot_general that
                            ACCUMULATES in bf16 — the repo-wide policy
                            (ops/lane_pack's exactness contract) is bf16
                            operands with f32 accumulation
                            (preferred_element_type), never bf16 sums.
  JL203 byte-budget         traced collective OPERAND BYTES per step drifted
                            from the manifest's ``bytes_per_step`` /
                            ``bytes_by_kind``. Counts alone miss comm-VOLUME
                            regressions: the same one ppermute per hop can
                            silently grow 4x when a quantized path falls
                            back to f32 (the dtype changes, the count does
                            not) or when an operand shape balloons. Bytes
                            are summed over the collective equations'
                            operand avals at tier-1 shapes — per STEP, same
                            scan-body-counts-once convention as JL201.

Everything here uses ``jax.make_jaxpr`` only: programs are traced, never
executed, so the whole budget check runs in tier-1 on the virtual CPU mesh.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tools.jaxlint.core import Finding

BUDGET_FILE = os.path.join("tools", "collective_budget.json")

# jaxpr primitive names that move bytes across the worker axis. axis_index
# is deliberately excluded: it reads the device grid, it does not
# communicate, so it is not part of the budget contract.
COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "all_gather", "all_to_all", "reduce_scatter",
    "psum_scatter", "ppermute", "pshuffle", "pbroadcast", "pgather",
}

# Fused ring-DMA hops (r10): on TPU these are in-kernel
# `make_async_remote_copy`s with NO collective primitive in the jaxpr; on
# the CPU tracing mesh the engine lowers them through a jit tagged with
# this name (must equal harp_tpu.ops.ring_dma.FUSED_HOP_NAME — tier-1
# asserts the two constants agree). The walker books a tagged call's
# operand bytes as the synthetic kind "fused_dma" and does NOT recurse into
# it — the inner ppermute is the transport the tag REPLACES, so counting
# both would double-charge, and counting only the ppermute would let a
# silent revert to a bare permute keep the same byte row. The manifest pins
# the kind per target (plus the explicit `fused_dma_bytes_per_step` field),
# so a fused schedule quietly degrading to ppermute moves bytes BETWEEN
# kinds and fails JL201/JL203.
FUSED_HOP_PREFIX = "ring_dma_fused_hop"


def _subjaxprs(eqn):
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    return n * dtype.itemsize


def _walk(jaxpr, counts: Dict[str, int], dtype_bad: List[str],
          nbytes: Dict[str, int]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if (name == "pjit"
                and str(eqn.params.get("name", "")).startswith(
                    FUSED_HOP_PREFIX)):
            counts["fused_dma"] = counts.get("fused_dma", 0) + 1
            nbytes["fused_dma"] = nbytes.get("fused_dma", 0) + sum(
                _aval_bytes(v) for v in eqn.invars)
            continue     # no recursion: the tag REPLACES the inner permute
        if name in COLLECTIVE_PRIMS:
            counts[name] = counts.get(name, 0) + 1
            # operand bytes = what the collective puts on the wire at tier-1
            # shapes (per-worker, inside shard_map). Summed over invars so a
            # multi-operand psum charges every payload.
            nbytes[name] = nbytes.get(name, 0) + sum(
                _aval_bytes(v) for v in eqn.invars)
        # dtype policy: no f64/c128 anywhere; bf16 dots must accumulate f32
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in ("float64", "complex128"):
                dtype_bad.append(f"{name} binds a {dt} value")
        if name == "dot_general":
            in_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                      for v in eqn.invars]
            out_dts = [str(getattr(getattr(v, "aval", None), "dtype", ""))
                       for v in eqn.outvars]
            if (in_dts and all(d == "bfloat16" for d in in_dts)
                    and all(d == "bfloat16" for d in out_dts)):
                dtype_bad.append(
                    "bf16 x bf16 dot_general accumulating in bf16 — pass "
                    "preferred_element_type=jnp.float32 (lane_pack "
                    "exactness contract: bf16 operands, f32 sums)")
        for sub in _subjaxprs(eqn):
            _walk(sub, counts, dtype_bad, nbytes)


def trace_target(name: str) -> Tuple[Dict[str, int], List[str],
                                     Dict[str, int]]:
    """Trace one registry target; returns (collective counts, dtype issues,
    collective operand bytes by kind).

    Counts/bytes are STATIC occurrences in the traced program. The hot loop
    of every target is a ``lax.scan`` over iterations, so a collective in
    the scan body counts once — i.e. the manifest records collectives **per
    step**, not per run (iteration counts are config, not contract).
    """
    import jax

    from tools.jaxlint import trace_targets

    fn, args = trace_targets.TARGETS[name]()
    closed = jax.make_jaxpr(fn)(*args)
    counts: Dict[str, int] = {}
    dtype_bad: List[str] = []
    nbytes: Dict[str, int] = {}
    _walk(closed.jaxpr, counts, dtype_bad, nbytes)
    return counts, dtype_bad, nbytes


def trace_all() -> Dict[str, Tuple[Dict[str, int], List[str],
                                   Dict[str, int]]]:
    from tools.jaxlint import trace_targets

    trace_targets.ensure_cpu_mesh()
    return {name: trace_target(name)
            for name in sorted(trace_targets.TARGETS)}


def load_budget(repo_root: str) -> Optional[dict]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budget(repo_root: str, traced) -> str:
    import jax

    path = os.path.join(repo_root, BUDGET_FILE)
    doc = {
        "_contract": (
            "Collectives-per-step manifest: static collective-primitive "
            "counts AND operand bytes in each model's traced step program "
            "at tier-1 shapes (tools/jaxlint/trace_targets.py). Tier-1 "
            "fails on ANY drift — an extra psum per step is a perf "
            "regression, a changed kind is a changed comm algorithm, and "
            "changed bytes at the same counts is a comm-VOLUME regression "
            "(e.g. a quantized path silently falling back to f32); "
            "regenerate deliberately with `python -m tools.jaxlint "
            "--update-budget` and review the diff. Counts/bytes are per "
            "STEP (scan bodies count once). fused_dma_bytes_per_step pins "
            "the bytes that move via in-kernel ring DMA "
            "(ops/ring_dma fused hops — tagged jits on the tracing mesh): "
            "a fused schedule silently reverting to bare ppermute moves "
            "these bytes between kinds and fails the gate."),
        "traced_with_jax": jax.__version__,
        "targets": {
            name: {
                "collectives": dict(sorted(counts.items())),
                "bytes_per_step": sum(nbytes.values()),
                "bytes_by_kind": dict(sorted(nbytes.items())),
                "fused_dma_bytes_per_step": nbytes.get("fused_dma", 0),
            }
            for name, (counts, _bad, nbytes) in sorted(traced.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return path


def check_budget(repo_root: str, traced=None) -> List[Finding]:
    """JL201/JL202/JL203 findings for the whole trace registry."""
    if traced is None:
        traced = trace_all()
    findings: List[Finding] = []

    def emit(code, checker, target, msg):
        findings.append(Finding(
            code=code, checker=checker, path=BUDGET_FILE, line=1,
            func=target, message=msg))

    budget = load_budget(repo_root)
    if budget is None:
        emit("JL201", "collective-budget", "<manifest>",
             f"{BUDGET_FILE} is missing — generate it with "
             f"`python -m tools.jaxlint --update-budget` and commit it")
        budget_targets = {}
    else:
        budget_targets = budget.get("targets", {})

    for name, (counts, dtype_bad, nbytes) in sorted(traced.items()):
        for issue in dtype_bad:
            emit("JL202", "dtype-policy", name, issue)
        if budget is None:
            continue
        if name not in budget_targets:
            emit("JL201", "collective-budget", name,
                 f"traced target {name!r} has no manifest entry — run "
                 f"--update-budget and review the new row")
            continue
        pinned = budget_targets[name].get("collectives", {})
        if dict(counts) != dict(pinned):
            drift = []
            for kind in sorted(set(counts) | set(pinned)):
                got, want = counts.get(kind, 0), pinned.get(kind, 0)
                if got != want:
                    drift.append(f"{kind}: traced {got} vs pinned {want}")
            emit("JL201", "collective-budget", name,
                 f"collective budget drift ({'; '.join(drift)}) — if "
                 f"intentional, regenerate with --update-budget and review "
                 f"the diff; if not, a step gained/lost communication")
        # JL203: comm volume. A manifest row predating byte budgets (no
        # bytes_per_step key) is itself a finding — the byte contract must
        # cover every target.
        pinned_total = budget_targets[name].get("bytes_per_step")
        pinned_kinds = budget_targets[name].get("bytes_by_kind", {})
        total = sum(nbytes.values())
        if pinned_total is None:
            emit("JL203", "byte-budget", name,
                 f"manifest entry {name!r} has no bytes_per_step — "
                 f"regenerate with --update-budget so the byte contract "
                 f"covers it")
        elif total != pinned_total or dict(nbytes) != dict(pinned_kinds):
            drift = []
            for kind in sorted(set(nbytes) | set(pinned_kinds)):
                got, want = nbytes.get(kind, 0), pinned_kinds.get(kind, 0)
                if got != want:
                    drift.append(f"{kind}: traced {got} B vs pinned {want} B")
            if total != pinned_total:
                drift.append(f"total: traced {total} B vs pinned "
                             f"{pinned_total} B")
            emit("JL203", "byte-budget", name,
                 f"collective byte-budget drift ({'; '.join(drift)}) — "
                 f"comm VOLUME changed at tier-1 shapes (same-count dtype "
                 f"widening, e.g. a quantized path silently reverting to "
                 f"f32, lands here); if intentional, --update-budget and "
                 f"review the diff")
        # fused ring-DMA contract: the explicit fused_dma_bytes_per_step
        # row must exist for any target whose trace moves bytes via the
        # fused engine, and must agree with the by-kind row (a fused target
        # silently reverting to ppermute already failed the kind drift
        # above — fused_dma bytes collapse to 0 and ppermute grows).
        traced_fused = nbytes.get("fused_dma", 0)
        pinned_fused = budget_targets[name].get("fused_dma_bytes_per_step")
        if traced_fused and pinned_fused is None:
            emit("JL203", "byte-budget", name,
                 f"target {name!r} moves {traced_fused} B/step via fused "
                 f"ring DMA but the manifest row has no "
                 f"fused_dma_bytes_per_step — regenerate with "
                 f"--update-budget so the fused contract covers it")
        elif (pinned_fused is not None
              and pinned_fused != pinned_kinds.get("fused_dma", 0)):
            emit("JL203", "byte-budget", name,
                 f"manifest inconsistency for {name!r}: "
                 f"fused_dma_bytes_per_step={pinned_fused} disagrees with "
                 f"bytes_by_kind fused_dma="
                 f"{pinned_kinds.get('fused_dma', 0)} — hand-edited row? "
                 f"regenerate with --update-budget")
    for name in sorted(set(budget_targets) - set(traced)):
        emit("JL201", "collective-budget", name,
             f"manifest entry {name!r} matches no trace target — stale row "
             f"(target renamed/removed); regenerate with --update-budget")
    return findings
