"""JL3xx concurrency checkers — the threaded-host-plane half of jaxlint.

Harp's value proposition is MPI-style collectives fused into a threaded
host runtime, and this repo now has exactly that shape: receive/serve
loops (``serve/router.py``), micro-batcher threads (``serve/batcher.py``),
exporter scrape threads (``telemetry/exporter.py``), watchdog and probe
threads (``parallel/failure.py``), scheduler pools (``sched/``). The race
bugs in that plane — ``StepLog.flush``'s drain, ``SLOWatchdog.observe``,
the exporter mid-scrape snapshot race, ``TimerReservoir.add`` — were each
caught only by hand review in PRs 10–12. This module turns that review
into a lint.

Codes:
  JL301 unguarded-shared-write    an instance attribute REBOUND (or a
                                  container field mutated) from a method
                                  reachable from two thread domains — or
                                  from a thread/callback entry writing a
                                  PUBLIC attribute, the class's read
                                  surface for other threads — with no
                                  enclosing ``with <lock>``.
  JL302 unsynchronized-rmw        a read-modify-write on shared state:
                                  ``self.x += ...`` (load + store, a lost
                                  update under interleaving) or
                                  check-then-act on a shared dict/deque
                                  (``if k in self.d: ... self.d[k]`` races
                                  a concurrent pop between test and use).
  JL303 lock-order-inversion      two methods of one class acquire the
                                  same two locks in OPPOSITE nesting
                                  order (directly, or via an intra-class
                                  call made while holding a lock) — the
                                  classic ABBA deadlock, which no test
                                  catches until the 3am hang.
  JL304 thread-lifecycle          a non-daemon thread with no ``join``
                                  on any close path: interpreter exit
                                  blocks on it forever (the atexit-close
                                  contract every host-plane class carries
                                  exists precisely to prevent this).

Thread-domain inference (class-local, deliberately conservative):

* **thread roots** — methods passed as ``threading.Thread(target=...)``
  (including nested functions defined inside a method, attributed to it),
  ``atexit.register``\\ ed methods, HTTP handler methods (``do_GET`` ...),
  and ``__call__`` (the hook/callback protocol: boundary hooks and reply
  callbacks are registered by one thread and invoked by another — the
  GangCollector/exporter ``/gang`` race of PR 12 lived exactly there).
* a method reachable (via ``self.m()`` calls) from a root runs on that
  root's thread; everything else is the "main" domain (public API runs on
  whatever thread calls it).
* an attribute is SHARED when its accesses span >= 2 domains, or when a
  non-main domain writes a public attribute (other threads read public
  attributes by convention; ``__init__`` writes are construction-time and
  never count).
* a write is GUARDED when lexically inside ``with self.<lock>`` (any
  attribute assigned ``threading.Lock/RLock/Condition()`` in the class,
  or whose name contains ``lock``/``cv``/``mutex``), or when the
  enclosing method follows the ``*_locked`` naming contract (documented
  caller-holds-the-lock).

Scope: only the threaded host-plane trees (``HOST_TREES``) — device code
and models run single-threaded SPMD and would drown the signal.

Suppression rides the shared allowlist (``(file, function, code)`` keys
with mandatory justifications; stale entries fail the run) — a benign
race (a sticky fail flag, a monotonic watermark) is allowlisted with its
reason, never silently skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.jaxlint.core import Finding

HOST_TREES = (
    "harp_tpu/serve/",
    "harp_tpu/telemetry/",
    "harp_tpu/parallel/",
    "harp_tpu/sched/",
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_SYNC_FACTORIES = _LOCK_FACTORIES | {"Event", "Semaphore", "BoundedSemaphore",
                                     "Barrier"}
_LOCKISH_NAME_PARTS = ("lock", "mutex", "_cv")
_HTTP_HANDLERS = {"do_GET", "do_POST", "do_PUT", "do_HEAD", "do_DELETE"}
# container-mutating method calls on self.<attr>.<m>(...) that write state
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert", "add",
             "update", "setdefault", "pop", "popleft", "popitem", "remove",
             "discard", "clear"}
# reads of self.<attr>.<m>(...) used in check-then-act tests
_CHECK_READS = {"get", "keys", "items", "values", "__contains__"}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a plain ``self.x`` attribute node, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_sync_factory_call(node: ast.AST) -> Optional[str]:
    """'Lock'/'Event'/... when node is ``threading.Lock()`` / ``Lock()``."""
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        if name in _SYNC_FACTORIES:
            return name
    return None


def _lockish(attr: str) -> bool:
    low = attr.lower()
    return any(p in low for p in _LOCKISH_NAME_PARTS)


class _Access:
    """One instance-attribute access inside a method body."""

    __slots__ = ("attr", "kind", "node", "guarded", "checked_first")

    def __init__(self, attr: str, kind: str, node: ast.AST, guarded: bool,
                 checked_first: bool = False):
        self.attr = attr
        self.kind = kind          # "read" | "write" | "aug" | "mut" | "sub"
        self.node = node
        self.guarded = guarded
        self.checked_first = checked_first   # mutation inside an unguarded
        #                                      membership/emptiness check on
        #                                      the same attr (check-then-act)

    @property
    def writes(self) -> bool:
        return self.kind != "read"


class _MethodScan(ast.NodeVisitor):
    """Walk ONE method body (nested functions attributed to the method,
    nested classes skipped — they are analyzed as their own class)."""

    def __init__(self, lock_attrs: Set[str], method_name: str):
        self.lock_attrs = lock_attrs
        self.always_guarded = method_name.endswith("_locked")
        self.accesses: List[_Access] = []
        self.calls_self: Set[str] = set()
        self.thread_targets: Set[str] = set()       # self.<m> Thread targets
        self.atexit_targets: Set[str] = set()
        self.threads: List[dict] = []               # Thread() creations
        self.lock_pairs: List[Tuple[str, str, ast.AST]] = []   # (outer, inner)
        self.calls_under_lock: List[Tuple[str, str, ast.AST]] = []
        self.locks_acquired: Set[str] = set()
        self._held: List[str] = []                  # lock-attr stack
        self._checked: List[Set[str]] = []          # check-then-act scopes

    # -- helpers ------------------------------------------------------------

    def _guarded(self) -> bool:
        return self.always_guarded or bool(self._held)

    def _checked_unguarded(self, attr: str) -> bool:
        return any(attr in scope for scope in self._checked)

    def _record(self, attr: str, kind: str, node: ast.AST) -> None:
        self.accesses.append(_Access(
            attr, kind, node, self._guarded(),
            checked_first=(kind != "read"
                           and self._checked_unguarded(attr))))

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """The lock identity a ``with`` context expr acquires, if any."""
        attr = _self_attr(expr)
        if attr is not None and (attr in self.lock_attrs or _lockish(attr)):
            return attr
        if isinstance(expr, ast.Name) and _lockish(expr.id):
            return expr.id
        # with self._lock_for(x): / acquire helpers — treat the callee name
        if isinstance(expr, ast.Call):
            name = _call_name(expr.func)
            if name is not None and _lockish(name):
                return name
        return None

    # -- structure ----------------------------------------------------------

    def visit_ClassDef(self, node):     # nested class: its own analysis
        return

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            lk = self._lock_name(item.context_expr)
            self.visit(item.context_expr)
            if lk is not None:
                self.locks_acquired.add(lk)
                for outer in self._held:
                    if outer != lk:
                        self.lock_pairs.append((outer, lk, node))
                self._held.append(lk)
                acquired.append(lk)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    visit_AsyncWith = visit_With

    def _test_checks(self, test: ast.AST) -> Set[str]:
        """Attrs whose state the test examines (membership, truthiness,
        .get/keys/...) — candidates for check-then-act."""
        out: Set[str] = set()
        for sub in ast.walk(test):
            attr = _self_attr(sub)
            if attr is not None:
                out.add(attr)
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _CHECK_READS):
                base = _self_attr(sub.func.value)
                if base is not None:
                    out.add(base)
        return out

    def _visit_branching(self, node):
        self.visit(node.test)
        checked = (self._test_checks(node.test)
                   if not self._guarded() else set())
        self._checked.append(checked)
        for stmt in node.body:
            self.visit(stmt)
        self._checked.pop()
        for stmt in getattr(node, "orelse", []):
            self.visit(stmt)

    visit_If = _visit_branching
    visit_While = _visit_branching

    # -- accesses -----------------------------------------------------------

    def _write_target(self, tgt: ast.AST, kind: str, node: ast.AST) -> None:
        attr = _self_attr(tgt)
        if attr is not None:
            self._record(attr, kind, node)
            return
        if isinstance(tgt, ast.Subscript):
            base = _self_attr(tgt.value)
            if base is not None:
                self._record(base, "sub" if kind == "write" else kind, node)
            else:
                self.visit(tgt.value)
            self.visit(tgt.slice)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._write_target(el, kind, node)
        else:
            self.visit(tgt)

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._write_target(tgt, "write", node)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._write_target(node.target, "write", node)
            self.visit(node.value)

    def visit_AugAssign(self, node):
        self._write_target(node.target, "aug", node)
        self.visit(node.value)

    def visit_Delete(self, node):
        for tgt in node.targets:
            self._write_target(tgt, "sub" if isinstance(tgt, ast.Subscript)
                               else "write", node)

    def visit_Attribute(self, node):
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._record(attr, "read", node)
        self.generic_visit(node)

    def visit_Call(self, node):
        # self.m(...) intra-class call edges
        f = node.func
        callee = _self_attr(f)
        if callee is not None and isinstance(f, ast.Attribute):
            self.calls_self.add(callee)
            if self._held:
                for lk in self._held:
                    self.calls_under_lock.append((lk, callee, node))
        # self.<attr>.<mutator>(...) container mutation
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            base = _self_attr(f.value)
            if base is not None:
                self._record(base, "mut", node)
        # threading.Thread(target=...) creation
        name = _call_name(f)
        if name == "Thread":
            self._scan_thread_ctor(node)
        elif name == "register" and isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) and f.value.id == "atexit":
            for arg in node.args[:1]:
                tgt = _self_attr(arg)
                if tgt is not None:
                    self.atexit_targets.add(tgt)
        self.generic_visit(node)

    def _scan_thread_ctor(self, node: ast.Call) -> None:
        target_method = None
        daemon = False
        for kw in node.keywords:
            if kw.arg == "target":
                tm = _self_attr(kw.value)
                if tm is not None:
                    target_method = tm
                elif isinstance(kw.value, ast.Name):
                    target_method = kw.value.id    # nested fn in this method
            elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        if target_method is not None:
            self.thread_targets.add(target_method)
        self.threads.append({"node": node, "daemon": daemon,
                             "stored_attr": None, "stored_name": None})

    # functions nested in the method are walked and attributed to the
    # method (their bodies run on whatever thread invokes the closure —
    # often another one). Guard state does NOT carry in: a closure DEFINED
    # under `with self._lock` executes later, when the definer's lock is
    # long released — treating its writes as guarded would silently pass
    # exactly the deferred-callback races this checker exists for.
    def _visit_nested_fn(self, body_stmts):
        held, checked = self._held, self._checked
        self._held, self._checked = [], []
        try:
            for stmt in body_stmts:
                self.visit(stmt)
        finally:
            self._held, self._checked = held, checked

    def visit_FunctionDef(self, node):
        self._visit_nested_fn(node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._visit_nested_fn([node.body])  # a lambda body is one expression


def _collect_lock_attrs(cls: ast.ClassDef) -> Tuple[Set[str], Set[str]]:
    """(lock attrs, all sync-primitive attrs) assigned anywhere in the
    class as ``self.x = threading.Lock()`` etc."""
    locks: Set[str] = set()
    sync: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            kind = _is_sync_factory_call(value) if value is not None else None
            if kind is None:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    sync.add(attr)
                    if kind in _LOCK_FACTORIES:
                        locks.add(attr)
    return locks, sync


def _closure(edges: Dict[str, Set[str]], roots: Set[str]) -> Set[str]:
    seen: Set[str] = set()
    stack = [r for r in roots]
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(edges.get(m, ()))
    return seen


def _annotate_thread_storage(scan_by_method: Dict[str, _MethodScan],
                             cls: ast.ClassDef) -> None:
    """Mark each Thread() creation with where its object lands (self.attr,
    a local name, or a container) and whether ``daemon`` is set later."""
    for mname, scan in scan_by_method.items():
        method = next((n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n.name == mname), None)
        if method is None:
            continue
        ctor_ids = {id(t["node"]): t for t in scan.threads}
        for node in ast.walk(method):
            if isinstance(node, ast.Assign) and id(node.value) in ctor_ids:
                rec = ctor_ids[id(node.value)]
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        rec["stored_attr"] = attr
                    elif isinstance(tgt, ast.Name):
                        rec["stored_name"] = tgt.id
        # late daemon flags: self.<attr>.daemon = True / <name>.daemon = True
        for node in ast.walk(method):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value):
                base = node.targets[0].value
                battr = _self_attr(base)
                for rec in scan.threads:
                    if battr is not None and rec["stored_attr"] == battr:
                        rec["daemon"] = True
                    elif (isinstance(base, ast.Name)
                          and rec["stored_name"] == base.id):
                        rec["daemon"] = True


def _join_calls(tree: ast.AST) -> List[Tuple[Optional[str], Optional[str]]]:
    """(self-attr, local-name) bases of every ``<x>.join(...)`` call."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            base = node.func.value
            # exclude str.join idiom: "sep".join(...) / "".join(...)
            if isinstance(base, ast.Constant):
                continue
            out.append((_self_attr(base),
                        base.id if isinstance(base, ast.Name) else None))
    return out


class _ClassReport:
    """Everything the four checkers need about one class."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.name = cls.name
        self.lock_attrs, self.sync_attrs = _collect_lock_attrs(cls)
        self.methods: Dict[str, _MethodScan] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _MethodScan(self.lock_attrs, node.name)
                for stmt in node.body:
                    scan.visit(stmt)
                self.methods[node.name] = scan
        _annotate_thread_storage(self.methods, cls)
        # thread roots: Thread targets + atexit hooks + HTTP handlers +
        # __call__ (hook/callback protocol)
        roots: Set[str] = set()
        for mname, scan in self.methods.items():
            roots |= scan.thread_targets & set(self.methods)
            roots |= scan.atexit_targets & set(self.methods)
            if scan.thread_targets - set(self.methods):
                # a Thread targeting a function NESTED in this method: the
                # closure's accesses are attributed to the method, so the
                # method itself becomes a thread root — over-approximate
                # (its non-closure accesses get the thread domain too),
                # which errs toward flagging, never toward missing the
                # closure-thread write
                roots.add(mname)
        roots |= _HTTP_HANDLERS & set(self.methods)
        if "__call__" in self.methods:
            roots.add("__call__")
        self.roots = roots
        edges = {m: s.calls_self & set(self.methods)
                 for m, s in self.methods.items()}
        self.reach_by_root = {r: _closure(edges, {r}) for r in roots}
        thread_methods = set().union(*self.reach_by_root.values()) \
            if self.reach_by_root else set()
        main_entries = set(self.methods) - thread_methods
        self.main_methods = _closure(edges, main_entries)
        self.domains: Dict[str, Set[str]] = {}
        for m in self.methods:
            d = {f"thread:{r}" for r, reach in self.reach_by_root.items()
                 if m in reach}
            if m in self.main_methods:
                d.add("main")
            self.domains[m] = d or {"main"}


def _shared_attrs(report: _ClassReport) -> Dict[str, Set[str]]:
    """attr -> union of access domains, for attrs shared across threads."""
    by_attr: Dict[str, Set[str]] = {}
    writes_outside_init: Set[str] = set()
    public_thread_writes: Set[str] = set()
    for mname, scan in report.methods.items():
        if mname == "__init__":
            continue
        doms = report.domains[mname]
        for acc in scan.accesses:
            if acc.attr in report.sync_attrs:
                continue
            by_attr.setdefault(acc.attr, set()).update(doms)
            if acc.writes:
                writes_outside_init.add(acc.attr)
                if (not acc.attr.startswith("_")
                        and any(d != "main" for d in doms)):
                    public_thread_writes.add(acc.attr)
    return {attr: doms for attr, doms in by_attr.items()
            if attr in writes_outside_init
            and (len(doms) >= 2 or attr in public_thread_writes)}


def _emit_shared_write_findings(report: _ClassReport, rel: str,
                                findings: List[Finding]) -> None:
    shared = _shared_attrs(report)
    if not shared:
        return
    for mname, scan in report.methods.items():
        if mname == "__init__":
            continue
        for acc in scan.accesses:
            if not acc.writes or acc.guarded or acc.attr not in shared:
                continue
            doms = sorted(shared[acc.attr])
            where = ", ".join(doms)
            if acc.kind == "aug" or acc.checked_first:
                what = ("read-modify-write" if acc.kind == "aug"
                        else "check-then-act mutation")
                findings.append(Finding(
                    "JL302", "unsynchronized-rmw", rel,
                    getattr(acc.node, "lineno", 0), mname,
                    f"{what} on shared self.{acc.attr} "
                    f"({report.name}; accessed from {where}) without a "
                    f"lock — interleaved threads lose updates (or race the "
                    f"test against a concurrent mutation); guard both "
                    f"sides with one class lock"))
            else:
                verb = {"write": "written", "sub": "item-assigned",
                        "mut": "mutated"}[acc.kind]
                findings.append(Finding(
                    "JL301", "unguarded-shared-write", rel,
                    getattr(acc.node, "lineno", 0), mname,
                    f"shared self.{acc.attr} {verb} without a lock "
                    f"({report.name}; accessed from {where}) — guard it "
                    f"with the class lock, or make it a threading.Event/"
                    f"queue if it is a signal"))


def _emit_lock_order_findings(report: _ClassReport, rel: str,
                              findings: List[Finding]) -> None:
    # transitive lock sets: locks a method acquires itself or via callees
    edges = {m: s.calls_self & set(report.methods)
             for m, s in report.methods.items()}
    # every acquisition counts, including sole (non-nested) ones: a caller
    # holding A that calls a method which takes B establishes A->B even
    # though neither method nests two withs itself
    direct = {m: set(s.locks_acquired) for m, s in report.methods.items()}
    acquires: Dict[str, Set[str]] = {}

    def acq_closure(m: str, seen: Set[str]) -> Set[str]:
        if m in acquires:
            return acquires[m]
        if m in seen:
            return direct.get(m, set())
        seen.add(m)
        out = set(direct.get(m, set()))
        for callee in edges.get(m, ()):  # locks taken by callees too
            out |= acq_closure(callee, seen)
        acquires[m] = out
        return out

    for m in report.methods:
        acq_closure(m, set())

    pairs: Dict[Tuple[str, str], Tuple[str, ast.AST]] = {}
    for m, s in report.methods.items():
        for (outer, inner, node) in s.lock_pairs:
            pairs.setdefault((outer, inner), (m, node))
        for (held, callee, node) in s.calls_under_lock:
            for inner in acquires.get(callee, ()):  # call takes more locks
                if inner != held:
                    pairs.setdefault((held, inner), (m, node))
    reported = set()
    for (a, b), (m, node) in sorted(pairs.items(),
                                    key=lambda kv: (kv[1][0], kv[0])):
        if (b, a) in pairs and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            m2, _n2 = pairs[(b, a)]
            findings.append(Finding(
                "JL303", "lock-order-inversion", rel,
                getattr(node, "lineno", 0), m,
                f"{report.name}.{m}() acquires {a} then {b}, but "
                f"{report.name}.{m2}() acquires {b} then {a} — ABBA "
                f"deadlock the moment both run concurrently; pick ONE "
                f"order (document it on the lock attributes) or collapse "
                f"to a single lock"))


def _emit_lifecycle_findings(report: _ClassReport, rel: str,
                             findings: List[Finding]) -> None:
    joins = _join_calls(report.cls)
    join_attrs = {a for a, _n in joins if a is not None}
    join_names = {n for _a, n in joins if n is not None}
    any_join = bool(joins)
    for mname, scan in report.methods.items():
        method_joins = {n for _a, n in _join_calls_method(report, mname)}
        for rec in scan.threads:
            if rec["daemon"]:
                continue
            attr, local = rec["stored_attr"], rec["stored_name"]
            if attr is not None and attr in join_attrs:
                continue
            if local is not None and (local in join_names
                                      or local in method_joins):
                continue
            if attr is None and local is None and any_join:
                continue      # escaped into a container; class does join
            where = (f"self.{attr}" if attr is not None
                     else (local or "an unbound Thread"))
            findings.append(Finding(
                "JL304", "thread-lifecycle", rel,
                getattr(rec["node"], "lineno", 0), mname,
                f"non-daemon thread ({where}) started in "
                f"{report.name}.{mname}() is never joined on any close "
                f"path — interpreter exit blocks on it forever; pass "
                f"daemon=True (and join in close()) or join it where the "
                f"object shuts down"))


def _join_calls_method(report: _ClassReport, mname: str):
    method = next((n for n in report.cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.name == mname), None)
    return _join_calls(method) if method is not None else []


def _module_function_lifecycle(mod: ast.AST, rel: str,
                               findings: List[Finding]) -> None:
    """JL304 for threads created in module-level functions (no class)."""
    for node in mod.body if isinstance(mod, ast.Module) else []:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scan = _MethodScan(set(), node.name)
        for stmt in node.body:
            scan.visit(stmt)
        joins = _join_calls(node)
        join_names = {n for _a, n in joins if n is not None}
        for rec in scan.threads:
            if rec["daemon"]:
                continue
            local = rec["stored_name"]
            if local is not None and local in join_names:
                continue
            if local is None and joins:
                continue
            findings.append(Finding(
                "JL304", "thread-lifecycle", rel,
                getattr(rec["node"], "lineno", 0), node.name,
                f"non-daemon thread ({local or 'unbound'}) started in "
                f"{node.name}() is never joined — interpreter exit blocks "
                f"on it; pass daemon=True or join it before returning"))


def check_concurrency(mod: ast.AST, rel: str, src: str) -> List[Finding]:
    """All four JL3xx codes over one host-plane module."""
    if not rel.startswith(HOST_TREES):
        return []
    findings: List[Finding] = []
    # classes at any nesting level (handler classes defined inside methods
    # — the exporter's BaseHTTPRequestHandler subclass — included)
    for node in ast.walk(mod):
        if isinstance(node, ast.ClassDef):
            report = _ClassReport(node)
            _emit_shared_write_findings(report, rel, findings)
            _emit_lock_order_findings(report, rel, findings)
            _emit_lifecycle_findings(report, rel, findings)
    _module_function_lifecycle(mod, rel, findings)
    return findings


THREAD_CHECKERS = [check_concurrency]
