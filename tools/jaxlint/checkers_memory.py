"""Static memory engine — the fourth jaxlint engine (JL4xx, ISSUE 19).

Runs liveness analysis (harp_tpu.aot.static_memory) over the ALREADY
traced jaxprs of both trace registries (checkers_jaxpr caches every
``make_jaxpr`` result, so the memory pass costs no extra tracing when the
collective engines ran first) and enforces:

  JL401 memory-budget      per-target ``peak_live_bytes`` /
                           ``resident_arg_bytes`` / ``transient_peak_ratio``
                           pinned in the ``memory`` section of
                           ``tools/collective_budget.json``. Drift fails CI
                           exactly like byte-drift (JL203) does — a program
                           whose static peak grows is a memory regression
                           that would otherwise ship invisibly until an OOM
                           on real HBM; regenerate deliberately with
                           ``--update-budget`` and review the diff.
  JL402 dropped-donation   a ``donate_argnums`` buffer that cannot alias
                           ANY output of matching shape/dtype in the traced
                           program. XLA drops such a donation with only a
                           warning: the caller believes the buffer is
                           reused, it is actually doubled. Every real hit
                           is fixed or individually justified in the
                           allowlist (keys ``(BUDGET_FILE, target,
                           "JL402")``).
  JL403 constant-bloat     a closed-over array above
                           ``CONST_BLOAT_BYTES`` baked into the jaxpr as a
                           constant — duplicated HBM per program plus a
                           retrace hazard (a new closure constant is a new
                           program; the JL103 cache idiom cannot help).
  JL404 transient-blowup   the liveness peak exceeds
                           ``TRANSIENT_BLOWUP_RATIO`` × the resident
                           argument bytes — the static signature of an
                           accidental full gather/broadcast
                           materialization (the static twin of the reshard
                           engine's chunk budget). The per-target RATIO is
                           also pinned by JL401, so drift below the
                           absolute guard still fails loudly.

Static numbers double as the model mall's planning input: the AOT store
records each artifact's row (``aot/store.py`` meta — metadata, never a key
axis) and tier-1 cross-checks ``Endpoint.resident_bytes()`` against the
static estimate.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tools.jaxlint.core import Finding

BUDGET_FILE = os.path.join("tools", "collective_budget.json")

# JL403: the largest closed-over constant any clean trace target carries
# today is ZERO bytes (state is passed as placed arguments everywhere —
# the endpoints/models resolve placement once and thread state explicitly,
# exactly so programs don't capture tables). 64 KiB leaves room for
# incidental lookup tables while still catching a factor table or
# parameter tree silently riding a closure.
CONST_BLOAT_BYTES = 64 * 1024

# JL404: the largest clean transient ratio in either registry is ~13.5x
# (kmeans_allreduce_int8 — dequantize-then-reduce widens the working set);
# 20x clears every committed program with margin while a full-table
# gather/broadcast at tier-1 shapes lands far above it. Drift BELOW this
# absolute guard still fails loudly: JL401 pins each target's exact ratio.
TRANSIENT_BLOWUP_RATIO = 20.0

MEMORY_FIELDS = ("resident_arg_bytes", "peak_live_bytes",
                 "transient_peak_ratio")


def _emit(findings: List[Finding], code: str, checker: str, target: str,
          msg: str) -> None:
    findings.append(Finding(code=code, checker=checker, path=BUDGET_FILE,
                            line=1, func=target, message=msg))


# -- per-jaxpr hazard checks (also the fixture surface for tests) -----------


def donation_findings(closed, target: str) -> List[Finding]:
    """JL402 for one traced program (static_memory.dropped_donations)."""
    from harp_tpu.aot import static_memory

    findings: List[Finding] = []
    for d in static_memory.dropped_donations(closed):
        _emit(findings, "JL402", "dropped-donation", target,
              f"donated buffer {d.aval} ({d.nbytes} B) in jit "
              f"{d.jit_name!r} aliases NO output of matching shape/dtype — "
              f"XLA drops the donation with only a warning, so the buffer "
              f"the caller believes is reused is actually doubled; remove "
              f"the donate_argnums entry (or return a matching-aval "
              f"output), or justify it in the allowlist")
    return findings


def const_findings(closed, target: str) -> List[Finding]:
    """JL403 for one traced program: closed-over constants above the
    bloat threshold."""
    from harp_tpu.aot import static_memory

    findings: List[Finding] = []
    for c in static_memory.captured_consts(closed):
        if c.nbytes >= CONST_BLOAT_BYTES:
            _emit(findings, "JL403", "constant-bloat", target,
                  f"closed-over {c.dtype}{list(c.shape)} constant "
                  f"({c.nbytes} B ≥ {CONST_BLOAT_BYTES} B) is baked into "
                  f"the jaxpr — duplicated HBM per program and a retrace "
                  f"hazard; pass it as a placed argument instead")
    return findings


def transient_findings(closed, target: str) -> List[Finding]:
    """JL404 for one traced program: liveness peak vs resident args."""
    from harp_tpu.aot import static_memory

    findings: List[Finding] = []
    res = static_memory.analyze_liveness(closed.jaxpr)
    if (res.resident_arg_bytes > 0
            and res.peak_live_bytes
            > TRANSIENT_BLOWUP_RATIO * res.resident_arg_bytes):
        ratio = res.peak_live_bytes / res.resident_arg_bytes
        _emit(findings, "JL404", "transient-blowup", target,
              f"liveness peak {res.peak_live_bytes} B is {ratio:.1f}x the "
              f"{res.resident_arg_bytes} B resident argument set (limit "
              f"{TRANSIENT_BLOWUP_RATIO:g}x), at eqn "
              f"#{res.peak_eqn_index} ({res.peak_eqn_primitive}) — an "
              f"accidental full gather/broadcast materialization; chunk "
              f"the transfer (the reshard engine's bounded schedule) or "
              f"raise the budget deliberately")
    return findings


def hazard_findings(closed, target: str) -> List[Finding]:
    """JL402 + JL403 + JL404 for one traced program."""
    return (donation_findings(closed, target)
            + const_findings(closed, target)
            + transient_findings(closed, target))


# -- registry-wide pass ------------------------------------------------------


def trace_memory_all() -> Dict[str, dict]:
    """JL401 rows for EVERY target in both registries (single-process and
    gang-mode), keyed by target name. Reuses checkers_jaxpr's trace cache:
    when the collective engines already traced a target this re-analyzes
    the cached jaxpr at zero trace cost."""
    from tools.jaxlint import checkers_jaxpr, trace_targets

    # the virtual mesh MUST exist before the harp_tpu package import below
    # pulls jax in (same ordering contract as checkers_jaxpr)
    trace_targets.ensure_cpu_mesh()
    from harp_tpu.aot import static_memory

    rows: Dict[str, dict] = {}
    for name in sorted(trace_targets.TARGETS):
        closed, _args, _link = checkers_jaxpr.traced_target(name)
        rows[name] = static_memory.memory_row(closed)
    for name in sorted(trace_targets.GANG_TARGETS):
        closed, _args, _link = checkers_jaxpr.traced_target(name, gang=True)
        rows[name] = static_memory.memory_row(closed)
    return rows


def check_memory_hazards() -> List[Finding]:
    """JL402/JL403/JL404 over both registries (raw — the caller routes
    these through the allowlist, unlike the JL401 manifest drift which is
    never suppressible)."""
    from tools.jaxlint import checkers_jaxpr, trace_targets

    trace_targets.ensure_cpu_mesh()
    findings: List[Finding] = []
    for name in sorted(trace_targets.TARGETS):
        closed, _args, _link = checkers_jaxpr.traced_target(name)
        findings.extend(hazard_findings(closed, name))
    for name in sorted(trace_targets.GANG_TARGETS):
        closed, _args, _link = checkers_jaxpr.traced_target(name, gang=True)
        findings.extend(hazard_findings(closed, name))
    return findings


def load_memory_rows(repo_root: str) -> Optional[Dict[str, dict]]:
    path = os.path.join(repo_root, BUDGET_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f).get("memory")


def check_memory_budget(repo_root: str,
                        mem: Optional[Dict[str, dict]] = None
                        ) -> List[Finding]:
    """JL401: the traced memory rows vs the manifest's ``memory`` section
    — exact equality per field, stale/missing rows loud (the check_budget
    contract applied to HBM instead of the wire)."""
    if mem is None:
        mem = trace_memory_all()
    findings: List[Finding] = []
    pinned_rows = load_memory_rows(repo_root)
    if pinned_rows is None:
        _emit(findings, "JL401", "memory-budget", "<manifest>",
              f"{BUDGET_FILE} has no memory section but {len(mem)} targets "
              f"trace — regenerate with `python -m tools.jaxlint "
              f"--update-budget` and commit the memory rows")
        return findings
    for name, row in sorted(mem.items()):
        if name not in pinned_rows:
            _emit(findings, "JL401", "memory-budget", name,
                  f"traced target {name!r} has no memory row — run "
                  f"--update-budget and review the new row")
            continue
        pinned = pinned_rows[name]
        drift = []
        for field in MEMORY_FIELDS:
            got, want = row.get(field), pinned.get(field)
            if got != want:
                drift.append(f"{field}: traced {got} vs pinned {want}")
        if drift:
            _emit(findings, "JL401", "memory-budget", name,
                  f"static memory-budget drift ({'; '.join(drift)}) — the "
                  f"program's HBM footprint moved at tier-1 shapes (a "
                  f"grown peak is the OOM that ships invisibly; a grown "
                  f"resident set changes what the model mall can "
                  f"co-locate); if intentional, --update-budget and "
                  f"review the diff")
    for name in sorted(set(pinned_rows) - set(mem)):
        _emit(findings, "JL401", "memory-budget", name,
              f"memory row {name!r} matches no trace target — stale row "
              f"(target renamed/removed); regenerate with --update-budget")
    return findings
