"""jaxlint runner: ``python -m tools.jaxlint [options] [repo_root]``.

Exit status is nonzero on ANY active finding, stale allowlist entry,
allowlist schema error, or collective-budget drift. ``--update-budget``
retraces every registry target and rewrites ``tools/collective_budget.json``
(commit the diff deliberately — it is the per-step communication contract).
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="AST + jaxpr static analysis for harp_tpu")
    parser.add_argument("root", nargs="?", default=None,
                        help="repo root (default: the checkout this file "
                             "lives in)")
    parser.add_argument("--ast-only", action="store_true",
                        help="skip the jaxpr engine (no model tracing)")
    parser.add_argument("--jaxpr-only", action="store_true",
                        help="skip the AST engine")
    parser.add_argument("--update-budget", action="store_true",
                        help="retrace all targets and rewrite "
                             "tools/collective_budget.json")
    args = parser.parse_args(argv)
    if args.ast_only and args.jaxpr_only:
        parser.error("--ast-only and --jaxpr-only are mutually exclusive "
                     "(together they would check nothing and report clean)")
    if args.ast_only and args.update_budget:
        parser.error("--update-budget needs the jaxpr engine; drop "
                     "--ast-only")

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)

    from tools.jaxlint.allowlist import ALLOWLIST
    from tools.jaxlint.checkers_ast import ast_checkers_for_repo
    from tools.jaxlint.core import (apply_allowlist, run_ast_checkers,
                                    validate_allowlist)

    problems = 0

    schema_errors = validate_allowlist(ALLOWLIST)
    for e in schema_errors:
        print(f"allowlist schema: {e}")
    problems += len(schema_errors)

    if not args.jaxpr_only:
        raw = run_ast_checkers(root, ast_checkers_for_repo(root))
        active, stale = apply_allowlist(raw, ALLOWLIST)
        for f in active:
            print(f)
        for s in stale:
            print(s)
        problems += len(active) + len(stale)
        print(f"ast engine: {len(active)} finding(s), {len(stale)} stale "
              f"allowlist entr(ies)")

    if not args.ast_only:
        from tools.jaxlint import checkers_jaxpr

        traced = checkers_jaxpr.trace_all()
        if args.update_budget:
            path = checkers_jaxpr.write_budget(root, traced)
            print(f"wrote {os.path.relpath(path, root)} "
                  f"({len(traced)} targets)")
        budget_findings = checkers_jaxpr.check_budget(root, traced)
        for f in budget_findings:
            print(f)
        problems += len(budget_findings)
        print(f"jaxpr engine: {len(traced)} targets traced, "
              f"{len(budget_findings)} finding(s)")

    if problems:
        print(f"jaxlint: {problems} problem(s)")
        return 1
    print("jaxlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
