"""jaxlint runner: ``python -m tools.jaxlint [options] [repo_root]``.

Exit status is nonzero on ANY active finding, stale allowlist entry,
allowlist schema error, or collective-budget drift (single-process AND
gang-mode rows). ``--update-budget`` retraces every registry target — both
engines — and rewrites ``tools/collective_budget.json`` (commit the diff
deliberately — it is the per-step communication contract).

``--json`` emits machine-readable findings, one JSON object per line
(``{"file", "line", "code", "checker", "func", "message", "allowlisted"}``;
stale allowlist entries ride the same stream with ``"code":
"stale-allowlist"``), so CI annotators and editors consume findings without
parsing the human text. Allowlisted findings are INCLUDED (flagged true) —
an editor wants to show the suppressed finding with its justification
context, and CI wants to count them; the exit code still ignores them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="AST + jaxpr + concurrency static analysis for harp_tpu")
    parser.add_argument("root", nargs="?", default=None,
                        help="repo root (default: the checkout this file "
                             "lives in)")
    parser.add_argument("--ast-only", action="store_true",
                        help="skip the jaxpr engines (no model tracing)")
    parser.add_argument("--jaxpr-only", action="store_true",
                        help="skip the AST engine (still traces both the "
                             "single-process and gang-mode registries)")
    parser.add_argument("--gang-only", action="store_true",
                        help="trace ONLY the gang-mode registry (the CI "
                             "gang-budget stage: virtual multi-process "
                             "mesh, counts/kinds/link-class bytes vs the "
                             "manifest)")
    parser.add_argument("--memory-only", action="store_true",
                        help="run ONLY the static memory engine (JL4xx, "
                             "ISSUE 19): liveness rows vs the manifest's "
                             "memory section (JL401), the donation audit "
                             "(JL402), constant-capture bloat (JL403), "
                             "and transient blowup (JL404) over BOTH "
                             "trace registries — the CI memory-budget "
                             "stage")
    parser.add_argument("--hlo-only", action="store_true",
                        help="run ONLY the lowered-HLO engine (JL5xx, "
                             "ISSUE 20): compile every cached trace "
                             "target post-SPMD (no execution) and check "
                             "compiler-inserted collectives (JL501), the "
                             "pinned hlo cost rows (JL502), sharding "
                             "propagation (JL503), and the per-device-"
                             "kind serving-dispatch matrix (JL504) — the "
                             "CI HLO gate")
    parser.add_argument("--update-budget", action="store_true",
                        help="retrace all targets (both engines) and "
                             "rewrite tools/collective_budget.json")
    parser.add_argument("--artifacts-only", action="store_true",
                        help="check ONLY the pinned compiled-program "
                             "manifest (tools/artifact_manifest.json): "
                             "re-export the harp_tpu.aot registry and "
                             "diff content hashes — a silently changed "
                             "compiled program is a finding (ISSUE 15)")
    parser.add_argument("--update-artifacts", action="store_true",
                        help="re-export the AOT artifact registry and "
                             "rewrite tools/artifact_manifest.json "
                             "(commit the diff deliberately — it is the "
                             "compiled-program contract)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="one finding per line as JSON (file, line, "
                             "code, message, allowlisted flag)")
    args = parser.parse_args(argv)
    if args.ast_only and (args.jaxpr_only or args.gang_only):
        parser.error("--ast-only excludes --jaxpr-only/--gang-only "
                     "(together they would check nothing and report clean)")
    if args.jaxpr_only and args.gang_only:
        parser.error("--jaxpr-only and --gang-only are mutually exclusive "
                     "(--gang-only would silently skip the single-process "
                     "budget check --jaxpr-only asks for)")
    if args.ast_only and args.update_budget:
        parser.error("--update-budget needs the jaxpr engines; drop "
                     "--ast-only")
    if args.gang_only and args.update_budget:
        parser.error("--update-budget retraces BOTH registries so the "
                     "manifest stays whole; drop --gang-only")
    if args.memory_only and (args.ast_only or args.jaxpr_only
                             or args.gang_only or args.artifacts_only):
        parser.error("--memory-only excludes the other engine selectors "
                     "(it runs exactly one engine already)")
    if args.memory_only and args.update_budget:
        parser.error("--update-budget retraces BOTH registries and "
                     "rewrites every manifest section together; drop "
                     "--memory-only")
    if args.artifacts_only and (args.ast_only or args.jaxpr_only
                                or args.gang_only):
        parser.error("--artifacts-only excludes the other engine "
                     "selectors (it runs exactly one engine already)")
    if args.artifacts_only and args.update_budget:
        parser.error("--update-budget needs the jaxpr engines; drop "
                     "--artifacts-only (or use --update-artifacts)")
    if args.hlo_only and (args.ast_only or args.jaxpr_only
                          or args.gang_only or args.memory_only
                          or args.artifacts_only):
        parser.error("--hlo-only excludes the other engine selectors "
                     "(it runs exactly one engine already)")
    if args.hlo_only and args.update_budget:
        parser.error("--update-budget retraces BOTH registries and "
                     "rewrites every manifest section together; drop "
                     "--hlo-only")

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)

    from tools.jaxlint.allowlist import ALLOWLIST
    from tools.jaxlint.checkers_ast import ast_checkers_for_repo
    from tools.jaxlint.core import (apply_allowlist, run_ast_checkers,
                                    validate_allowlist)

    problems = 0

    def out_finding(f, allowlisted: bool) -> None:
        if args.as_json:
            print(json.dumps({
                "file": f.path, "line": f.line, "code": f.code,
                "checker": f.checker, "func": f.func, "message": f.message,
                "allowlisted": allowlisted}))
        elif not allowlisted:
            print(f)

    def out_note(msg: str, code: str = "stale-allowlist") -> None:
        if args.as_json:
            print(json.dumps({"file": "tools/jaxlint/allowlist.py",
                              "line": 0, "code": code, "checker": code,
                              "func": "<allowlist>", "message": msg,
                              "allowlisted": False}))
        else:
            print(msg)

    def status(msg: str) -> None:
        # progress/summary lines stay off stdout in --json mode so the
        # stream is pure JSONL for machine consumers
        if not args.as_json:
            print(msg)

    schema_errors = validate_allowlist(ALLOWLIST)
    for e in schema_errors:
        out_note(f"allowlist schema: {e}", code="allowlist-schema")
    problems += len(schema_errors)

    # the allowlist is one schema but one pool PER ENGINE (core.
    # split_allowlist): JL4xx keys belong to the memory engine, JL5xx to
    # the lowered-HLO engine (both keyed on the budget file + target),
    # everything else to the AST/concurrency engines — each pass applies
    # only its own pool so a cross-engine entry never reports stale
    from tools.jaxlint.core import split_allowlist

    pools = split_allowlist(ALLOWLIST)
    ast_allow, mem_allow, hlo_allow = (pools["ast"], pools["memory"],
                                       pools["hlo"])

    if not (args.jaxpr_only or args.gang_only or args.artifacts_only
            or args.memory_only or args.hlo_only):
        raw = run_ast_checkers(root, ast_checkers_for_repo(root))
        active, stale = apply_allowlist(raw, ast_allow)
        active_keys = {id(f) for f in active}
        for f in raw:
            out_finding(f, allowlisted=id(f) not in active_keys)
        for s in stale:
            out_note(s)
        problems += len(active) + len(stale)
        status(f"ast engine: {len(active)} finding(s), {len(stale)} stale "
               f"allowlist entr(ies)")

    if not (args.ast_only or args.artifacts_only or args.memory_only
            or args.hlo_only):
        from tools.jaxlint import checkers_jaxpr

        traced = None
        if not args.gang_only:
            traced = checkers_jaxpr.trace_all()
        gang = checkers_jaxpr.trace_gang_all()
        if args.update_budget:
            from tools.jaxlint import checkers_hlo, checkers_memory

            mem_rows = checkers_memory.trace_memory_all()
            hlo_section = checkers_hlo.build_hlo_section(root)
            path = checkers_jaxpr.write_budget(root, traced, gang,
                                               mem_rows, hlo_section)
            status(f"wrote {os.path.relpath(path, root)} "
                   f"({len(traced)} targets, {len(gang)} gang targets, "
                   f"{len(mem_rows)} memory rows, "
                   f"{len(hlo_section.get('targets', {}))} hlo rows)")
        if traced is not None:
            budget_findings = checkers_jaxpr.check_budget(root, traced)
            for f in budget_findings:
                out_finding(f, allowlisted=False)
            problems += len(budget_findings)
            status(f"jaxpr engine: {len(traced)} targets traced, "
                   f"{len(budget_findings)} finding(s)")
        gang_findings = checkers_jaxpr.check_gang_budget(root, gang)
        for f in gang_findings:
            out_finding(f, allowlisted=False)
        problems += len(gang_findings)
        status(f"gang engine: {len(gang)} gang-mode targets traced, "
               f"{len(gang_findings)} finding(s)")

    # the static memory engine (JL4xx, ISSUE 19): liveness rows vs the
    # manifest's memory section, donation audit, constant bloat, transient
    # blowup — over BOTH registries. Runs in the full default pass, under
    # --jaxpr-only (the telemetry gate re-checks memory rows too — the
    # traces are cached, so this costs analysis only), and as its own
    # --memory-only stage. JL401 drift is never suppressible (like
    # JL201/JL203); JL402-404 ride the allowlist contract.
    if not (args.ast_only or args.gang_only or args.artifacts_only
            or args.hlo_only):
        from tools.jaxlint import checkers_memory

        mem = checkers_memory.trace_memory_all()
        mem_findings = checkers_memory.check_memory_budget(root, mem)
        for f in mem_findings:
            out_finding(f, allowlisted=False)
        problems += len(mem_findings)
        hazards = checkers_memory.check_memory_hazards()
        h_active, h_stale = apply_allowlist(hazards, mem_allow)
        h_active_ids = {id(f) for f in h_active}
        for f in hazards:
            out_finding(f, allowlisted=id(f) not in h_active_ids)
        for s in h_stale:
            out_note(s)
        problems += len(h_active) + len(h_stale)
        status(f"memory engine: {len(mem)} targets analyzed, "
               f"{len(mem_findings) + len(h_active)} finding(s), "
               f"{len(h_stale)} stale allowlist entr(ies)")

    # the lowered-HLO engine (JL5xx, ISSUE 20): compile every cached
    # trace target post-SPMD — compilation only, nothing executes — and
    # check compiler-inserted collectives (JL501), the pinned compiled
    # cost rows (JL502), sharding propagation (JL503), and the per-
    # device-kind serving-dispatch matrix (JL504). Runs in the full
    # default pass and as its own --hlo-only CI stage. JL502/JL504
    # manifest drift is never suppressible; JL501/JL503 ride the JL5xx
    # allowlist pool.
    if args.hlo_only or not (args.ast_only or args.jaxpr_only
                             or args.gang_only or args.memory_only
                             or args.artifacts_only):
        from tools.jaxlint import checkers_hlo

        hlo_rows = checkers_hlo.trace_hlo_all()
        kind_rows = checkers_hlo.serving_dispatch_rows()
        hlo_findings = checkers_hlo.check_hlo_budget(root, hlo_rows,
                                                     kind_rows)
        for f in hlo_findings:
            out_finding(f, allowlisted=False)
        problems += len(hlo_findings)
        hlo_hazards = checkers_hlo.check_hlo_hazards()
        hz_active, hz_stale = apply_allowlist(hlo_hazards, hlo_allow)
        hz_active_ids = {id(f) for f in hz_active}
        for f in hlo_hazards:
            out_finding(f, allowlisted=id(f) not in hz_active_ids)
        for s in hz_stale:
            out_note(s)
        problems += len(hz_active) + len(hz_stale)
        status(f"hlo engine: {len(hlo_rows)} targets lowered, "
               f"{len(kind_rows)} serving dispatches on "
               f"{checkers_hlo.running_device_kind()!r}, "
               f"{len(hlo_findings) + len(hz_active)} finding(s), "
               f"{len(hz_stale)} stale allowlist entr(ies)")

    # the compiled-program manifest (ISSUE 15): re-export the AOT registry
    # and hash-diff against tools/artifact_manifest.json — runs in the
    # full default pass and under --artifacts-only (the telemetry and
    # gang stages re-trace enough already; a program drift shows up here
    # regardless of which stage's pass caught it first)
    if args.artifacts_only or args.update_artifacts or not (
            args.ast_only or args.jaxpr_only or args.gang_only
            or args.memory_only or args.hlo_only):
        import shutil
        import tempfile

        from tools.jaxlint.trace_targets import ensure_cpu_mesh

        ensure_cpu_mesh()
        from harp_tpu.aot import manifest as aot_manifest

        workdir = tempfile.mkdtemp(prefix="harp-aot-lint-")
        try:
            if args.update_artifacts:
                path = aot_manifest.update(root, workdir)
                status(f"wrote {os.path.relpath(path, root)}")
            else:
                art_findings = aot_manifest.check(root, workdir)
                for msg in art_findings:
                    out_note(msg, code="artifact-drift")
                problems += len(art_findings)
                status(f"artifact engine: manifest checked, "
                       f"{len(art_findings)} finding(s)")
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    if problems:
        status(f"jaxlint: {problems} problem(s)")
        return 1
    status("jaxlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
