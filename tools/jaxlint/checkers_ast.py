"""AST checkers over ``harp_tpu/`` — the lexical half of jaxlint.

Codes:
  JL101 collective-divergence  collective call inside a branch conditioned on
                               rank / process_index / worker id — one member
                               enters the collective, the rest don't: the
                               gang deadlocks (DrJAX arXiv:2403.07128 makes
                               the static-checkability argument).
  JL102 axis-name              collective ``axis_name`` literal that no mesh /
                               shard_map / canonical axis constant declares —
                               an unbound axis fails only at trace time, a
                               *misbound* one (typo'd "worker") fails at 3am
                               on the gang.
  JL103 retrace-hazard         jit/spmd wrappers rebuilt per call (immediately
                               invoked, or constructed inside a loop without a
                               cache guard), mutable default args on jitted
                               functions, jitted closures over ``global``
                               state — each retraces or shares state silently.
  JL104 host-sync-hot-loop     ``.item()`` / ``block_until_ready`` /
                               ``np.asarray`` inside a Python loop in a
                               fit/train path — a device→host sync per
                               iteration serializes the dispatch pipeline
                               (benchmark/timing.py is exempt: timing is the
                               one place a sync is the point).
  JL105 broad-except           ``except Exception``/bare except without a
                               justified allowlist entry — swallows the
                               KeyboardInterrupt-adjacent world and hides
                               gang member death behind a warning.
  JL106 scatter                ``.at[...].add/.set`` in the device hot trees
                               (folded from r6 tools/lint_scatter.py — XLA
                               lowers these to the serializing TPU scatter
                               unit, measured 8.8x slower than the
                               one-hot-GEMM form; route via ops/lane_pack).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from tools.jaxlint.core import Finding, FuncStackVisitor

# --------------------------------------------------------------------------
# collective-call recognition (shared by JL101/JL102)
# --------------------------------------------------------------------------

# Distinctive collective names — unambiguous from any call shape.
_COLLECTIVE_ANY = {
    "psum", "psum_like", "psum_scatter", "pmean", "pmax", "pmin",
    "all_gather", "ppermute", "pshuffle", "all_to_all", "reduce_scatter",
    "allreduce", "allgather", "rotate_map", "send_recv",
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "rotate_scan", "pipelined_rotation",
}
# Generic words that are collectives only when called on a known module.
_COLLECTIVE_SCOPED = {"broadcast", "reduce", "gather", "push", "pull",
                      "rotate", "regroup", "barrier"}
_COLLECTIVE_MODULES = {"lax_ops", "table_ops", "rotation", "multihost_utils"}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def collective_call_name(node: ast.Call) -> Optional[str]:
    """Name of the collective this call performs, or None."""
    name = _call_name(node.func)
    if name is None:
        return None
    if name in _COLLECTIVE_ANY:
        return name
    if name in _COLLECTIVE_SCOPED and isinstance(node.func, ast.Attribute):
        base = node.func.value
        if isinstance(base, ast.Name) and base.id in _COLLECTIVE_MODULES:
            return name
        if isinstance(base, ast.Attribute) and base.attr in _COLLECTIVE_MODULES:
            return name
    return None


# --------------------------------------------------------------------------
# JL101 collective-divergence
# --------------------------------------------------------------------------

_RANK_CALLS = {"process_index", "worker_id", "axis_index", "getSelfID"}
_RANK_ATTRS = {"process_index", "master_id", "is_master"}
_RANK_NAMES = {"rank", "wid", "worker_id", "my_rank", "self_id", "proc_rank"}


def _mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _call_name(node.func) in _RANK_CALLS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _RANK_ATTRS:
            return True
        if isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
    return False


def check_collective_divergence(mod: ast.AST, rel: str, src: str
                                ) -> List[Finding]:
    class V(FuncStackVisitor):
        def __init__(self, rel_path):
            super().__init__(rel_path)
            self.rank_branch: List[int] = []   # lineno of rank-If being walked

        def _walk_branch(self, stmts):
            for stmt in stmts:
                self.visit(stmt)

        def visit_If(self, node):
            self.visit(node.test)
            if _mentions_rank(node.test):
                self.rank_branch.append(node.lineno)
                self._walk_branch(node.body)
                self._walk_branch(node.orelse)
                self.rank_branch.pop()
            else:
                self._walk_branch(node.body)
                self._walk_branch(node.orelse)

        def visit_IfExp(self, node):
            self.visit(node.test)
            if _mentions_rank(node.test):
                self.rank_branch.append(node.lineno)
                self.visit(node.body)
                self.visit(node.orelse)
                self.rank_branch.pop()
            else:
                self.visit(node.body)
                self.visit(node.orelse)

        def visit_Call(self, node):
            if self.rank_branch:
                cname = collective_call_name(node)
                if cname is not None:
                    self.emit(
                        "JL101", "collective-divergence", node,
                        f"collective {cname}() inside a rank-conditional "
                        f"branch (if at line {self.rank_branch[-1]}) — only "
                        f"some gang members reach it; the rest wait forever. "
                        f"Hoist the collective out of the branch and mask "
                        f"its CONTRIBUTION instead (lax_ops.broadcast/"
                        f"reduce show the masked-psum idiom)")
            self.generic_visit(node)

    v = V(rel)
    v.visit(mod)
    return v.findings


# --------------------------------------------------------------------------
# JL102 axis-name
# --------------------------------------------------------------------------

# Canonical axes declared by harp_tpu.parallel.mesh (WORKERS/MODEL). Parsed
# from that module at scan time by gather_canonical_axes(); this fallback
# keeps fixture-level checking working standalone.
_FALLBACK_AXES = {"workers", "model"}

_AXIS_DECL_CALLS = {"Mesh", "make_mesh", "shard_map", "P", "PartitionSpec",
                    "AxisName"}


def gather_canonical_axes(repo_root: str) -> Set[str]:
    """Axis-name constants declared module-level in parallel/mesh.py."""
    path = os.path.join(repo_root, "harp_tpu", "parallel", "mesh.py")
    axes: Set[str] = set()
    try:
        with open(path, encoding="utf-8") as f:
            mod = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set(_FALLBACK_AXES)
    for stmt in mod.body:
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id.isupper():
                    axes.add(stmt.value.value)
    return axes or set(_FALLBACK_AXES)


def _module_declared_axes(mod: ast.AST) -> Set[str]:
    """String literals this module itself binds as axes: ALL_CAPS string
    constants, and literals inside Mesh/shard_map/P(...) declarations."""
    declared: Set[str] = set()
    for node in ast.walk(mod):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and any(isinstance(t, ast.Name) and t.id.isupper()
                        for t in node.targets)):
            declared.add(node.value.value)
        if (isinstance(node, ast.Call)
                and _call_name(node.func) in _AXIS_DECL_CALLS):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                                str):
                    declared.add(sub.value)
    return declared


# collectives taking axis_name positionally right after the operand
_AXIS_POS1 = {"psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
              "pshuffle", "all_to_all", "psum_scatter", "axis_index",
              "psum_like"}


def make_axis_name_checker(canonical_axes: Optional[Set[str]] = None):
    axes_base = set(canonical_axes) if canonical_axes else set(_FALLBACK_AXES)

    def check_axis_name(mod: ast.AST, rel: str, src: str) -> List[Finding]:
        known = axes_base | _module_declared_axes(mod)

        class V(FuncStackVisitor):
            def visit_Call(self, node):
                cname = collective_call_name(node)
                if cname is None and _call_name(node.func) != "axis_index":
                    self.generic_visit(node)
                    return
                lit = None
                for kw in node.keywords:
                    if kw.arg == "axis_name" and isinstance(
                            kw.value, ast.Constant) and isinstance(
                            kw.value.value, str):
                        lit = kw.value.value
                name = cname or "axis_index"
                if (lit is None and name in _AXIS_POS1
                        and len(node.args) > (0 if name == "axis_index"
                                              else 1)):
                    pos = node.args[0 if name == "axis_index" else 1]
                    if isinstance(pos, ast.Constant) and isinstance(
                            pos.value, str):
                        lit = pos.value
                if lit is not None and lit not in known:
                    self.emit(
                        "JL102", "axis-name", node,
                        f"collective {name}() names axis {lit!r}, which no "
                        f"enclosing mesh/shard_map declaration or canonical "
                        f"axis constant ({sorted(known)}) binds — use "
                        f"mesh.WORKERS/lax_ops' axis_name default, or "
                        f"declare the axis in this module")
                self.generic_visit(node)

        v = V(rel)
        v.visit(mod)
        return v.findings

    return check_axis_name


check_axis_name = make_axis_name_checker()   # standalone/fixture default


# --------------------------------------------------------------------------
# JL103 retrace-hazard
# --------------------------------------------------------------------------

def _is_jit_like(node: ast.Call) -> Optional[str]:
    """'jit' / 'spmd' / 'pjit' if this call constructs a compiled wrapper."""
    name = _call_name(node.func)
    if name in {"jit", "pjit", "spmd"}:
        return name
    return None


def _decorated_jit(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = _call_name(dec.func)
            if name in {"jit", "pjit"}:
                return True
            if name == "partial" and dec.args and _call_name(
                    dec.args[0]) in {"jit", "pjit"}:
                return True
        elif _call_name(dec) in {"jit", "pjit"}:
            return True
    return False


_MUTABLE_DEFAULT = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                    ast.SetComp)


def check_retrace_hazard(mod: ast.AST, rel: str, src: str) -> List[Finding]:
    class V(FuncStackVisitor):
        def __init__(self, rel_path):
            super().__init__(rel_path)
            self.loop_depth = 0
            self.cached_nodes: set = set()   # id() of jit calls whose
            #   result is stored into a container (cache[key] = jit(...))

        def enter_function(self, node):
            if not _decorated_jit(node):
                return
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, _MUTABLE_DEFAULT) or (
                        isinstance(d, ast.Call) and _call_name(d.func)
                        in {"list", "dict", "set"}):
                    self.emit(
                        "JL103", "retrace-hazard", d,
                        f"jitted {node.name}() has a mutable default "
                        f"argument — defaults are captured at trace time "
                        f"and shared across calls; pass it explicitly or "
                        f"mark it static", func=node.name)
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Global):
                    self.emit(
                        "JL103", "retrace-hazard", stmt,
                        f"jitted {node.name}() closes over `global` state — "
                        f"the traced program bakes in the value at trace "
                        f"time and never sees updates (silent staleness, "
                        f"not a retrace)", func=node.name)

        def _visit_loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _visit_loop
        visit_While = _visit_loop

        def visit_Assign(self, node):
            # the sanctioned in-loop compile idiom stores the wrapper in a
            # container keyed on shape/config (`self._fns[key] = jit(...)`)
            # — the subscript target IS the cache, so the wrapper survives
            # the iteration. A plain-name bind (`f = jit(...)`) in a loop
            # does not, whatever `if ... not in ...` guards surround it.
            if (isinstance(node.value, ast.Call) and _is_jit_like(node.value)
                    and any(isinstance(t, ast.Subscript)
                            for t in node.targets)):
                self.cached_nodes.add(id(node.value))
            self.generic_visit(node)

        def visit_Call(self, node):
            inner = node.func
            if isinstance(inner, ast.Call) and _is_jit_like(inner):
                self.emit(
                    "JL103", "retrace-hazard", node,
                    f"{_is_jit_like(inner)}(...) built and invoked in one "
                    f"expression — the wrapper (and its trace cache) is "
                    f"discarded after the call, so every invocation "
                    f"retraces; bind the compiled callable once (the "
                    f"`self._fns[key]` idiom) or use session.run for "
                    f"documented one-shots")
            elif (_is_jit_like(node) and self.loop_depth > 0
                    and id(node) not in self.cached_nodes):
                self.emit(
                    "JL103", "retrace-hazard", node,
                    f"{_is_jit_like(node)}(...) constructed inside a loop "
                    f"and not stored into a cache container — a fresh "
                    f"wrapper per iteration retraces every time; hoist it "
                    f"or bind it `cache[key] = ...` keyed on the "
                    f"shape/config")
            self.generic_visit(node)

    v = V(rel)
    v.visit(mod)
    return v.findings


# --------------------------------------------------------------------------
# JL104 host-sync-hot-loop
# --------------------------------------------------------------------------

_EXEMPT_SYNC_FILES = {"harp_tpu/benchmark/timing.py"}
_HOT_FUNC_PREFIXES = ("fit", "train")


def check_host_sync(mod: ast.AST, rel: str, src: str) -> List[Finding]:
    if rel in _EXEMPT_SYNC_FILES:
        return []

    class V(FuncStackVisitor):
        def __init__(self, rel_path):
            super().__init__(rel_path)
            self.loop_depth = 0

        def _visit_loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _visit_loop
        visit_While = _visit_loop

        def _in_hot_fit(self) -> bool:
            return (self.loop_depth > 0
                    and any(f.startswith(_HOT_FUNC_PREFIXES)
                            for f in self.func_stack))

        def visit_Call(self, node):
            if self._in_hot_fit():
                f = node.func
                sync = None
                if isinstance(f, ast.Attribute):
                    if f.attr == "item" and not node.args:
                        sync = ".item()"
                    elif f.attr == "block_until_ready":
                        sync = "block_until_ready()"
                    elif (f.attr == "asarray"
                          and isinstance(f.value, ast.Name)
                          and f.value.id in {"np", "numpy", "onp"}):
                        sync = "np.asarray()"
                if sync:
                    self.emit(
                        "JL104", "host-sync-hot-loop", node,
                        f"{sync} inside a Python loop in "
                        f"{'/'.join(self.func_stack)} — a device→host sync "
                        f"per iteration stalls the dispatch pipeline; keep "
                        f"device values on device until after the loop "
                        f"(benchmark/timing.py is the only sanctioned "
                        f"timing-sync site)")
            self.generic_visit(node)

    v = V(rel)
    v.visit(mod)
    return v.findings


# --------------------------------------------------------------------------
# JL105 broad-except
# --------------------------------------------------------------------------

def check_broad_except(mod: ast.AST, rel: str, src: str) -> List[Finding]:
    class V(FuncStackVisitor):
        def visit_ExceptHandler(self, node):
            broad = None
            t = node.type
            if t is None:
                broad = "bare except:"
            else:
                names = [n for n in (t.elts if isinstance(t, ast.Tuple)
                                     else [t])]
                for n in names:
                    nm = n.id if isinstance(n, ast.Name) else (
                        n.attr if isinstance(n, ast.Attribute) else None)
                    if nm in {"Exception", "BaseException"}:
                        broad = f"except {nm}"
            if broad:
                self.emit(
                    "JL105", "broad-except", node,
                    f"{broad} — narrow it to the failures this site can "
                    f"actually handle (ImportError for optional deps, "
                    f"TypeError for hashability probes, ...), or allowlist "
                    f"it with the reason the blast radius must stay wide")
            self.generic_visit(node)

    v = V(rel)
    v.visit(mod)
    return v.findings


# --------------------------------------------------------------------------
# JL106 scatter (folded from tools/lint_scatter.py, r6)
# --------------------------------------------------------------------------

_SCATTER_METHODS = {"add", "set", "mul", "divide", "min", "max", "power",
                    "apply"}
HOT_TREES = ("harp_tpu/models/", "harp_tpu/ops/")


def is_at_indexed_update(node: ast.Call) -> Optional[str]:
    """Matches ``<expr>.at[<idx>].<method>(...)``; returns the method name."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _SCATTER_METHODS):
        return None
    sub = f.value
    if not isinstance(sub, ast.Subscript):
        return None
    base = sub.value
    if isinstance(base, ast.Attribute) and base.attr == "at":
        return f.attr
    return None


def check_scatter(mod: ast.AST, rel: str, src: str) -> List[Finding]:
    if not rel.startswith(HOT_TREES):
        return []

    class V(FuncStackVisitor):
        def visit_Call(self, node):
            m = is_at_indexed_update(node)
            if m is not None:
                self.emit(
                    "JL106", "scatter", node,
                    f".at[...].{m} — XLA lowers indexed updates to the "
                    f"serializing TPU scatter unit (8.8x slower than the "
                    f"one-hot-GEMM form, PERF.md r4/r5); route through "
                    f"ops/lane_pack (gemm_scatter/densify_rows) or "
                    f"allowlist with a reason")
            self.generic_visit(node)

    v = V(rel)
    v.visit(mod)
    return v.findings


# Registry (axis-name is instantiated per-repo-root by the runner so it can
# parse the canonical axes; this module-level list is the fixture default).
AST_CHECKERS = [
    check_collective_divergence,
    check_axis_name,
    check_retrace_hazard,
    check_host_sync,
    check_broad_except,
    check_scatter,
]


def ast_checkers_for_repo(repo_root: str):
    # the JL3xx concurrency engine rides the same registry: one walk of the
    # tree serves the lexical checkers and the thread-domain inference
    from tools.jaxlint.checkers_threads import check_concurrency

    return [
        check_collective_divergence,
        make_axis_name_checker(gather_canonical_axes(repo_root)),
        check_retrace_hazard,
        check_host_sync,
        check_broad_except,
        check_scatter,
        check_concurrency,
    ]
