"""Model step-function trace registry for the jaxpr engine.

Each target builds a model at TIER-1 shapes (the same tiny configs the test
suite runs on the 8-worker virtual CPU mesh) and returns the compiled step
callable plus already-placed inputs, so ``jax.make_jaxpr`` can trace the
whole training program WITHOUT executing it. The traced collective counts
are what ``tools/collective_budget.json`` pins — an extra psum per step (or
a variant silently changing its collective kind) is a performance-contract
drift exactly like a bench-number regression (arXiv:2112.01075 treats
per-step collective counts as a first-class redistribution contract).

Prepare-side work DOES run on host+device (tiny device_puts); the step
program itself is only traced. Keep shapes small — every target is traced
in tier-1.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Dict, Tuple

NUM_WORKERS = 8


def ensure_cpu_mesh() -> None:
    """Force the tier-1 tracing platform: 8 virtual CPU devices.

    Mirrors tests/conftest.py. Must run before jax initializes a backend;
    inside pytest the conftest has already done the identical setup.
    """
    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{NUM_WORKERS}").strip()
    import jax

    # the image's sitecustomize force-selects the TPU backend via
    # jax.config — override back before any backend initializes (conftest
    # does the same); tracing must not hold a real accelerator
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", False)
    if len(jax.devices()) < NUM_WORKERS:
        raise RuntimeError(
            f"jaxlint tracing needs {NUM_WORKERS} virtual CPU devices but "
            f"found {len(jax.devices())} — jax initialized before "
            f"ensure_cpu_mesh() could set XLA_FLAGS")


def _session():
    from harp_tpu.session import HarpSession

    return HarpSession(num_workers=NUM_WORKERS)


def _rng():
    import numpy as np

    return np.random.default_rng(0)


# -- builders: () -> (callable, args) --------------------------------------


def _kmeans(comm: str, quant=None):
    def build():
        from harp_tpu.models import kmeans as km

        sess = _session()
        model = km.KMeans(sess, km.KMeansConfig(8, 16, iterations=2,
                                                comm=comm, quant=quant))
        rng = _rng()
        pts = rng.normal(size=(64, 16)).astype("float32")
        p, c = model.prepare(pts, pts[:8].copy())
        return model._fit, (p, c)

    return build


def _lda(**cfg_kw):
    def build():
        from harp_tpu.models import lda

        sess = _session()
        model = lda.LDA(sess, lda.LDAConfig(num_topics=4, vocab=96,
                                            epochs=2, **cfg_kw))
        docs = _rng().integers(0, 96, size=(16, 12))
        key, data, seed, _meta = model.prepare(docs, seed=0)
        return model._fns[key], (*data, seed)

    return build


def _lda_subblock():
    from harp_tpu.models import lda

    sess = _session()
    model = lda.LDA(sess, lda.LDAConfig(num_topics=4, vocab=2048, epochs=2,
                                        vocab_sub_block=128))
    docs = _rng().integers(0, 2048, size=(16, 12))
    key, data, seed, _meta = model.prepare(docs, seed=0)
    return model._fns[key], (*data, seed)


def _sgd_mf(quant=None, fused_dma=False):
    def build():
        from harp_tpu.models import sgd_mf

        sess = _session()
        cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.1, epochs=2,
                                 minibatches_per_hop=2, quant=quant,
                                 fused_dma=fused_dma)
        model = sgd_mf.SGDMF(sess, cfg)
        rng = _rng()
        n = 400
        rows = rng.integers(0, 64, size=n)
        cols = rng.integers(0, 48, size=n)
        vals = rng.normal(size=n).astype("float32")
        layout, data, w0, h0, meta = model.prepare(rows, cols, vals, 64, 48)
        key = model._program(layout, cfg.minibatches_per_hop, cfg.epochs,
                             meta[6])
        return model._compiled[key], (*data, w0, h0)

    return build


def _als():
    from harp_tpu.models import als

    sess = _session()
    cfg = als.ALSConfig(rank=8, lam=0.05, iterations=2, implicit=False)
    model = als.ALS(sess, cfg)
    rng = _rng()
    n = 400
    rows = rng.integers(0, 80, size=n)
    cols = rng.integers(0, 64, size=n)
    vals = rng.normal(size=n).astype("float32")
    key, placed, _, _ = model.prepare(rows, cols, vals, 80, 64)
    return model._fns[key], placed


def _pagerank():
    from harp_tpu.models import pagerank as pr

    sess = _session()
    cfg = pr.PageRankConfig(iterations=2)
    rng = _rng()
    n_edges, n_vertices = 200, 64
    src = rng.integers(0, n_vertices, size=n_edges).astype("int32")
    dst = rng.integers(0, n_vertices, size=n_edges).astype("int32")
    nbr, mask, deg = pr.pad_out_edges(src, dst, n_vertices, sess.num_workers)
    v_pad = nbr.shape[0]
    fn = sess.spmd(
        lambda a, b, c: pr._pagerank(a, b, c, n_vertices, v_pad, cfg),
        in_specs=(sess.shard(),) * 3,
        out_specs=(sess.replicate(), sess.replicate()))
    return fn, (sess.scatter(nbr), sess.scatter(mask), sess.scatter(deg))


def _nn():
    import jax.numpy as jnp

    from harp_tpu.models import nn

    sess = _session()
    cfg = nn.NNConfig(layers=(8,), num_classes=3, lr=0.1, batch_size=8,
                      epochs=2)
    rng = _rng()
    x = rng.normal(size=(64, 10)).astype("float32")
    y = rng.integers(0, 3, size=64).astype("int32")
    params0 = nn.init_params((10, 8, 3), seed=0)
    fn = sess.spmd(
        lambda a, t, p: nn._train(a, t, p, cfg),
        in_specs=(sess.shard(), sess.shard(), sess.replicate()),
        out_specs=(sess.replicate(), sess.replicate()))
    return fn, (sess.scatter(jnp.asarray(x)), sess.scatter(jnp.asarray(y)),
                params0)


def _serve_classify():
    from harp_tpu.models import nn
    from harp_tpu.serve import endpoints as serve_ep

    sess = _session()
    model = nn.MLPClassifier(sess, nn.NNConfig(layers=(8,), num_classes=3))
    model.params = nn.init_params((12, 8, 3), seed=0)
    ep = serve_ep.classify_from_nn(sess, model, name="nn")
    x = _rng().normal(size=(ep.bucket_sizes[0], 12)).astype("float32")
    fn, args, _n, _bucket = ep.prepared(x)
    return fn, args


def _serve_topk():
    from harp_tpu.serve import endpoints as serve_ep

    sess = _session()
    rng = _rng()
    uf = rng.normal(size=(64, 8)).astype("float32")
    items = rng.normal(size=(32, 8)).astype("float32")
    ep = serve_ep.TopKEndpoint(sess, "mf", uf, items, k=4)
    ids = rng.integers(0, 64, size=ep.bucket_sizes[0])
    fn, args, _n, _bucket = ep.prepared(ids)
    return fn, args


def _serve_topk_rebalanced():
    from harp_tpu.serve import endpoints as serve_ep

    sess = _session()
    rng = _rng()
    uf = rng.normal(size=(64, 8)).astype("float32")
    items = rng.normal(size=(32, 8)).astype("float32")
    ep = serve_ep.TopKEndpoint(sess, "mf", uf, items, k=4)
    ep.rebalance(1)       # owner-map routed dispatch (ISSUE 11 rebalance)
    ids = rng.integers(0, 64, size=ep.bucket_sizes[0])
    fn, args, _n, _bucket = ep.prepared(ids)
    return fn, args


def _serve_topk_int8():
    """The QUANTIZED serving dispatch (ISSUE 17): same 3 all_to_alls +
    1 psum as serve_topk_mf, but the route-back all_to_all carries packed
    int8 factor rows (r+4 bytes/row instead of 4r f32 bytes) — the pinned
    byte row sits strictly below the f32 twin's, so a silent f32 revert
    grows bytes at the same counts and fails JL203."""
    from harp_tpu.serve import endpoints as serve_ep

    sess = _session()
    rng = _rng()
    uf = rng.normal(size=(64, 8)).astype("float32")
    items = rng.normal(size=(32, 8)).astype("float32")
    ep = serve_ep.TopKEndpoint(sess, "mf", uf, items, k=4, quant="int8")
    ids = rng.integers(0, 64, size=ep.bucket_sizes[0])
    fn, args, _n, _bucket = ep.prepared(ids)
    return fn, args


def _multiclass_svm_pairs():
    """The multiclass one-vs-one TRAINING program: all pair machines in one
    vmapped rotation-blocked kernel-dual program (KernelSVM.
    _fit_padded_pairs builds exactly this spmd: pairs on the vmap batch
    axis, rows sharded over workers on axis 1) at a 3-class tier-1 shape
    — the r8 dryrun leg's step program, now budget-pinned (ISSUE 14
    satellite)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from harp_tpu.models import svm as svm_mod

    sess = _session()
    cfg = svm_mod.KernelSVMConfig(kernel="rbf", iterations=3, power_iters=2)
    p, n_pad, d = 3, 64, 6              # 3 classes -> 3 pair machines
    fn = sess.spmd(
        jax.vmap(lambda a, t, c: svm_mod._train_kernel_dual(a, t, c, cfg)),
        in_specs=(sess.shard(1),) * 3,
        out_specs=(sess.shard(1), sess.replicate(), sess.replicate()))
    rng = _rng()
    xb = rng.normal(size=(p, n_pad, d)).astype("float32")
    yb = np.sign(rng.normal(size=(p, n_pad))).astype("float32")
    cb = np.full((p, n_pad), cfg.c, "float32")
    return fn, (sess.scatter(jnp.asarray(xb), axis=1),
                sess.scatter(jnp.asarray(yb), axis=1),
                sess.scatter(jnp.asarray(cb), axis=1))


def _distributed_sort():
    """The r10 sort/quantiles dryrun leg's heavy program: the distributed
    odd-even block sort (sharded output assembled by fetch) at the tier-1
    shape — its ppermute ladder is exactly the cross-worker traffic the
    gang rows exist to price."""
    import jax.numpy as jnp

    from harp_tpu.models import stats as stats_mod
    from harp_tpu.ops import linalg

    sess = _session()
    s = stats_mod.Sorting(sess)
    fn = s._compile("sort", lambda a: linalg.distributed_sort(a), 0,
                    extra_sharded_out=1)
    x = _rng().standard_normal((128, 6)).astype("float32")
    return fn, (sess.scatter(jnp.asarray(x)),)


def _csr_cov():
    """The r10 CSR covariance/PCA dryrun leg's step program: the blocked
    densify-GEMM gram from CSR input over the mesh (sparse_gram_stats) —
    CSRPCA rides the same program plus a replicated eigensolve."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sparse as sp

    sess = _session()
    n, dim = 128, 12
    rows, cols, vals = datagen.sparse_points(n, dim, 0.2, seed=9)
    cov = sp.CSRCovariance(sess)
    idx, val, mask, real = cov._layout(rows, cols, vals, n, dim)
    cov._stats(rows, cols, vals, n, dim)     # populate the compile cache
    fn = cov._fns[(idx.shape, dim)]
    return fn, (sess.scatter(idx), sess.scatter(val), sess.scatter(mask),
                sess.scatter(real))


def _kmeans_fileload():
    """The r11 file-load dryrun leg: K-means fed from part-files on disk
    through the io/loaders pipeline (list_files glob -> split -> threaded
    CSV load -> scatter). Pinning it as its own gang row asserts the
    ingestion path feeds the SAME step program as the in-memory twin —
    bytes identical, or the leg's bitwise-parity promise broke."""
    import shutil
    import tempfile

    import numpy as np

    from harp_tpu.io import datagen, loaders
    from harp_tpu.models import kmeans as km

    sess = _session()
    io_dir = tempfile.mkdtemp(prefix="harp-lint-io-")
    try:
        pts = datagen.dense_points(64, 16, seed=11, num_clusters=8)
        for i, part in enumerate(np.array_split(pts, 4)):
            np.savetxt(os.path.join(io_dir, f"part-{i:05d}.csv"), part,
                       delimiter=",", fmt="%.8e")
        paths = loaders.list_files(os.path.join(io_dir, "part-*"))
        splits = loaders.split_files(paths, 2)
        loaded = loaders.load_dense_csv([p for s in splits for p in s])
        loaded = loaders.truncate_to_workers(loaded, NUM_WORKERS)
    finally:
        shutil.rmtree(io_dir, ignore_errors=True)
    model = km.KMeans(sess, km.KMeansConfig(8, 16, iterations=2,
                                            comm="regroupallgather"))
    p, c = model.prepare(loaded, loaded[:8].copy())
    return model._fit, (p, c)


def _reshard(schedule: str):
    def build():
        import numpy as np

        from harp_tpu.collectives import reshard as rs
        from harp_tpu.models.sgd_mf import identity_assign, serpentine_assign

        sess = _session()
        rng = _rng()
        # a W=4 checkpointed factor table re-sharded onto the 8-worker
        # tracing mesh: serpentine old maps, identity new maps, 97 valid
        # rows (prime — the padded-slot edge is in the traced program) and
        # a 512 B chunk budget so the schedule runs MULTIPLE rounds: the
        # pinned bytes-per-step row IS the per-round foreign footprint,
        # which a schedule degrading toward a full gather would grow.
        n, r = 97, 8
        old_world, old_rpb, new_rpb = 4, 28, 16
        old = rs.block_layout(
            serpentine_assign(rng.integers(1, 9, n), old_world), old_rpb,
            old_world)
        new = rs.block_layout(identity_assign(n, NUM_WORKERS), new_rpb,
                              NUM_WORKERS)
        saved = rng.standard_normal(
            (old_world * old_rpb, r)).astype("float32")
        fill = sess.scatter(np.zeros((NUM_WORKERS * new_rpb, r),
                                     np.float32))
        plan = rs.plan_factor_reshard(old, old_world, new, NUM_WORKERS, n,
                                      r * 4, chunk_bytes=512,
                                      schedule=schedule)
        return rs.prepare_reshard(sess, saved, plan, fill)

    return build


def _ingest_coo_regroup():
    """r19 (ISSUE 18): the streaming-ingestion COO regroup step program
    (io/pipeline.regroup_coo_device) — parsed nonzeros routed to their
    row-block owner by the SAME bounded all_to_all schedule as the reshard
    engine, packed as 20 B (row i64, col i64, val f32) records.  A 512 B
    chunk budget at the tier-1 shape keeps multiple rounds in the traced
    program, so the pinned bytes-per-step row IS the per-round foreign
    footprint: a regroup degrading toward a whole-table gather grows it
    and fails JL203."""
    import numpy as np

    from harp_tpu.collectives import reshard as rs
    from harp_tpu.io import pipeline as pl

    sess = _session()
    rng = _rng()
    n, num_rows = 300, 97
    rows = rng.integers(0, num_rows, n).astype(np.int64)
    cols = rng.integers(0, 64, n).astype(np.int64)
    vals = rng.standard_normal(n).astype(np.float32)
    plan, counts, cap = rs.plan_coo_regroup(rows, num_rows, NUM_WORKERS,
                                            chunk_bytes=512)
    rec = pl.pack_coo(rows, cols, vals)
    fill = sess.scatter(np.zeros((NUM_WORKERS * cap, 5), np.int32))
    return rs.prepare_reshard(sess, rec, plan, fill)


# Registry: target name -> builder returning (traceable callable, args).
# Names are the manifest keys — renaming one is a manifest change.
# The *_int8/*_bf16 rows pin the QUANTIZED step programs: their byte rows
# sit far below the f32 twins', so a quantized path silently reverting to
# f32 (same collective counts, 2-4x the operand bytes) fails JL203 exactly
# like count drift fails JL201.
# The *_fused rows (r10) pin the fused ring-DMA step programs: the wt/H
# rotation hops trace as the tagged `fused_dma` kind (checkers_jaxpr
# FUSED_HOP_PREFIX) with the SAME bytes the f32 ppermute moved — a fused
# schedule silently reverting to bare ppermute swaps those bytes back
# between kinds and fails the gate. lda_cgs_quantwt_int8 pins the
# satellite quantized wt-block rotation (ISSUE 9): its ppermute bytes sit
# far below lda_cgs's because the (vpb, K) block ships int8+scales.
# The serve_* rows (r11) pin the ONLINE-SERVING dispatch programs:
# serve_classify_nn must stay at ZERO collectives (replicated params,
# sharded query batch — a psum/all_gather sneaking into the resident
# predict dispatch fails JL201 loudly), and serve_topk_mf must stay at
# exactly the 3 all_to_alls of the keyval DistributedKV lookup
# (bucket_route payload + mask, route_back) — the parameter-server pull
# path the top-k endpoint serves from. Retrace policing is the other half:
# the endpoints hold one compiled fn per (model, batch-bucket) in the
# JL103-clean `self._fns[bucket]` cache, and tests/test_serve.py asserts
# exactly one trace per bucket under live traffic.
TARGETS: Dict[str, Callable[[], Tuple[Callable, tuple]]] = {
    "kmeans_regroupallgather": _kmeans("regroupallgather"),
    "kmeans_allreduce": _kmeans("allreduce"),
    "kmeans_pushpull": _kmeans("pushpull"),
    "kmeans_bcastreduce": _kmeans("bcastreduce"),
    "kmeans_rotation": _kmeans("rotation"),
    "kmeans_allreduce_int8": _kmeans("allreduce", quant="int8"),
    "kmeans_regroupallgather_bf16": _kmeans("regroupallgather",
                                            quant="bf16"),
    "lda_cgs": _lda(),
    "lda_cgs_fused": _lda(fused_dma=True),
    "lda_cgs_quantwt_int8": _lda(quant="int8", quant_wt=True),
    "lda_cgs_subblock128": _lda_subblock,
    "sgd_mf_dense": _sgd_mf(),
    "sgd_mf_dense_int8": _sgd_mf(quant="int8"),
    "sgd_mf_dense_fused": _sgd_mf(fused_dma=True),
    "als_explicit": _als,
    "pagerank": _pagerank,
    "nn_mlp": _nn,
    "serve_classify_nn": _serve_classify,
    "serve_topk_mf": _serve_topk,
    # r12 (ISSUE 11): the on-device reshard step programs. The a2a row pins
    # ONE all_to_all per round whose operand bytes ARE the per-round
    # foreign-row budget (chunk_bytes at the traced shape) — a schedule
    # silently degrading toward a full gather (bigger rounds, or a
    # fall-back all_gather) changes kinds/bytes and fails JL201/JL203. The
    # ring row pins the per-shift ppermute schedule (rides lax_ops.rotate,
    # so DCN chunking composes). serve_topk_mf_rebalanced pins the
    # owner-map-routed serving dispatch a rebalance() switches to: the
    # SAME 3 all_to_alls as serve_topk_mf — rebalancing moves shards, it
    # must never add a collective to the request path.
    "reshard_factor_a2a": _reshard("alltoall"),
    "reshard_factor_ring": _reshard("ring"),
    "serve_topk_mf_rebalanced": _serve_topk_rebalanced,
    # r17 (ISSUE 17): the int8 serving dispatch — the quantized twin of
    # serve_topk_mf (same collective counts, packed int8 route-back), the
    # budget row that makes a silent f32 revert on the REQUEST path as
    # loud as one on a training path.
    "serve_topk_mf_int8": _serve_topk_int8,
    # r19 (ISSUE 18): the streaming-ingestion distributed COO regroup — the
    # per-round all_to_all operand bytes ARE the ≤ chunk_bytes budget
    # (8 peers x 3 records x 20 B = 480 B at the traced 512 B budget); a
    # regroup silently reverting to a whole-table host/device gather
    # changes kinds or grows bytes and fails JL201/JL203.
    "ingest_coo_regroup": _ingest_coo_regroup,
}


# -- gang-mode targets (ISSUE 13 tentpole, the carried "jaxlint multi-host
# budgets" ROADMAP item) ----------------------------------------------------
#
# A gang-mode target is a `dryrun_multichip` step program traced on the
# SAME 8-worker tracing mesh but with a declared multi-process topology:
# ``processes`` hosts x ``devices_per_process`` local devices, the workers
# axis laid out contiguously per process (exactly how
# ``parallel.distributed.initialize`` + ``make_mesh`` place a real gang —
# mp_smoke's 2x4 layout). The program is SPMD, so every process traces the
# SAME jaxpr; what differs per process is the SHARD it owns and which hops
# cross the data-center network instead of on-pod ICI. The manifest row
# therefore pins, besides counts/bytes:
#
# * ``per_process_shard_shapes`` — the per-process block of every program
#   input (a replicated dim stays global; a workers-sharded dim is the
#   global extent over ``processes``). A drifted shard shape means the
#   partitioner changed what each HOST holds — a resharding contract break
#   (arXiv:2112.01075 treats the redistribution layout as first-class),
#   JL201.
# * ``bytes_by_link`` — ``bytes_by_kind`` split DCN vs ICI with the
#   ring-edge/peer model in checkers_jaxpr.split_bytes_by_link, gated on
#   ``mesh.axis_link_class(WORKERS)`` (gang launchers hint the workers
#   axis "dcn" at bootstrap; the DrJAX-style multi-mesh programs of
#   arXiv:2403.07128 make that DCN/ICI split first-class). Growing DCN
#   bytes at fixed counts is exactly the cross-pod regression the
#   single-process rows cannot see, JL203.
#
# The workloads are the dryrun_multichip gang's own exercises (mp_smoke):
# K-means over both parallelism families, SGD-MF, and LDA.

GANG_PROCESSES = 2
GANG_DEVICES_PER_PROCESS = 4     # 2 x 4 = NUM_WORKERS, mp_smoke's layout

GANG_TARGETS: Dict[str, Callable[[], Tuple[Callable, tuple]]] = {
    "gang2x4_kmeans_regroupallgather": _kmeans("regroupallgather"),
    "gang2x4_kmeans_rotation": _kmeans("rotation"),
    "gang2x4_sgd_mf_dense": _sgd_mf(),
    "gang2x4_lda_cgs": _lda(),
    # ISSUE 14 satellite — the dryrun legs that landed without gang rows
    # (ROADMAP: "new gang workloads should add gang rows as they land"):
    # multiclass one-vs-one SVM (r8), distributed sort (the r10
    # sort/quantiles leg's comm-heavy half), CSR covariance (the r10
    # cov/PCA leg's step program), and the file-load leg's K-means step
    # (pins that the ingestion pipeline feeds a byte-identical program).
    "gang2x4_multiclass_svm_pairs": _multiclass_svm_pairs,
    "gang2x4_distributed_sort": _distributed_sort,
    "gang2x4_csr_cov": _csr_cov,
    "gang2x4_kmeans_fileload": _kmeans_fileload,
}
