"""jaxlint — static analysis for the harp_tpu training stack.

Run: ``python -m tools.jaxlint`` (AST + jaxpr engines, nonzero exit on any
finding, stale allowlist entry, or budget drift). See README "Static
analysis" and tools/jaxlint/core.py for the allowlist contract.
"""

from tools.jaxlint.core import (  # noqa: F401
    Finding, apply_allowlist, run_ast_checkers, validate_allowlist,
)
from tools.jaxlint.allowlist import ALLOWLIST  # noqa: F401
