"""The shared jaxlint allowlist — every exemption in one place.

Keys are ``(repo-relative file, enclosing function, finding code)``; values
are MANDATORY justifications (core.MIN_JUSTIFICATION chars minimum — "ok"
is not a reason). Entries whose key matches no live finding FAIL the run as
stale: prune the entry when the exempted code is fixed, or it silently
pre-approves the next violation in that function.

JL106 (scatter) entries migrated verbatim from the r6 tools/lint_scatter.py
ALLOWLIST — same functions, same reasons, now carrying the code column.
"""

from __future__ import annotations

from tools.jaxlint.core import Allowlist

ALLOWLIST: Allowlist = {
    # -- JL106 scatter: cold prepare-side layout or gated legacy strategies --
    ("harp_tpu/models/sgd_mf.py", "densify", "JL106"):
        "prepare-time slab densification: runs ONCE per layout, scatters "
        "into a slab too wide for a one-hot GEMM (slab_elems lanes); the "
        "per-epoch hot path is pure stripe GEMMs",
    ("harp_tpu/models/sgd_mf.py", "mb_step", "JL106"):
        "legacy layout='sparse' minibatch update, kept for data too large "
        "to densify; documented ~25M samples/s gather/scatter wall — the "
        "dense masked-stripe layout IS the hot path",
    ("harp_tpu/models/sparse.py", "sparse_kmeans_stats", "JL106"):
        "strategy='gather' phantom-count correction: the gated legacy "
        "strategy for very-sparse-very-wide data (default is the "
        "lane_pack densify-GEMM, 13x faster on the bench shape)",
    ("harp_tpu/models/solvers.py", "bwd", "JL106"):
        "L-BFGS two-loop recursion alpha write: O(history) scalars per "
        "OUTER optimizer step, not per-sample work",
    ("harp_tpu/models/solvers.py", "step", "JL106"):
        "L-BFGS (s, y, rho) ring-buffer history write: O(history) rows "
        "per outer step",
    ("harp_tpu/models/forest.py", "one_tree", "JL106"):
        "per-tree feature mask init: O(dim) bits once per tree build, "
        "never inside the per-sample scoring loop",
    ("harp_tpu/ops/linalg.py", "body", "JL106"):
        "distributed-sort permutation bookkeeping: O(W) control-plane "
        "rows per merge round, not data-plane traffic",

    # -- JL103 retrace-hazard: sanctioned one-shot / per-config compiles ----
    ("harp_tpu/session.py", "run", "JL103"):
        "session.run IS the documented one-shot entry point (compile and "
        "invoke once, for scripts and prepare-time programs); callers that "
        "need the trace cache hold the callable from session.spmd instead",
    # -- JL104 host-sync-hot-loop: syncs that ARE the semantics ------------
    ("harp_tpu/models/kmeans.py", "fit_checkpointed", "JL104"):
        "chunk-boundary checkpoint write: the D2H snapshot of the "
        "replicated centroids is the save payload — one sync per "
        "save_every-iteration compiled chunk, not per iteration",
    ("harp_tpu/models/lda.py", "fit_checkpointed", "JL104"):
        "chunk-boundary checkpoint write of the chain state (z, wt): the "
        "D2H fetch is the save payload, once per save_every-epoch chunk",
    ("harp_tpu/models/sgd_mf.py", "fit_checkpointed", "JL104"):
        "chunk-boundary checkpoint write of the factor blocks: the D2H "
        "fetch is the save payload, once per save_every-epoch chunk",
    ("harp_tpu/models/sgd_mf.py", "fit_adaptive", "JL104"):
        "the per-epoch sync is the MEASUREMENT: the hop-budget tuner "
        "(reference adjustMiniBatch) times each compiled epoch on the host "
        "to pick the next budget — without the sync there is no signal",
    ("harp_tpu/models/sgxsimu.py", "fit", "JL104"):
        "the per-iteration sync is the SIMULATION: the enclave-cost model "
        "sleeps the modeled overhead after each COMPLETED chunk "
        "(reference's concurrent simuOverhead); unsynced dispatches would "
        "overlap the sleeps and void the model",

    # -- JL3xx concurrency: benign-by-design cross-thread state ------------
    ("harp_tpu/parallel/failure.py", "_loop", "JL301"):
        "sticky fail-stop flag: the heartbeat thread only ever flips "
        "failed False->True and the main thread only reads it in ok() — "
        "monotonic single-writer boolean, GIL-atomic store, and a missed "
        "read costs one extra probe interval, never a lost failure (ok() "
        "keeps raising once set); a lock would add nothing but overhead "
        "on the per-iteration hot path",
    ("harp_tpu/telemetry/xprof.py", "_start", "JL301"):
        "XprofController state (trace_dir, remaining) is single-threaded "
        "by the StepLog contract: boundary hooks run ONLY on the training "
        "loop thread (add_boundary_hook docstring), and the cross-thread "
        "handoff is the trigger FILE polled by (mtime, size) token — the "
        "controller attrs never cross a thread",
    ("harp_tpu/telemetry/xprof.py", "_stop", "JL301"):
        "same StepLog single-thread contract as _start: remaining is only "
        "touched from boundary hooks on the training loop thread; the "
        "operator-facing side is the atomically-replaced trigger file, "
        "not these attributes",
    ("harp_tpu/telemetry/xprof.py", "__call__", "JL302"):
        "remaining -= 1 runs only on the training loop thread (StepLog "
        "boundary hooks are single-threaded by contract); the __call__ "
        "hook heuristic assumes callbacks may cross threads, which the "
        "xprof controller deliberately never does (its module docstring "
        "calls out why collective ops must stay boundary-aligned)",

    # -- JL105 broad-except: blast radius deliberately wide ----------------
    ("harp_tpu/io/pipeline.py", "_run", "JL105"):
        "the prefetch thread envelopes ANY producer failure (parse error, "
        "fsspec IO, device_put OOM) into the chunk queue so it re-raises "
        "on the CONSUMER's thread — same contract as DynamicScheduler's "
        "_TaskError; a narrowed except would hang the consumer on a "
        "missing sentinel instead",
    ("harp_tpu/aot/store.py", "load", "JL105"):
        "deserializing a stale/foreign artifact payload can raise "
        "anything the jax.export/StableHLO loader reaches; the contract "
        "is degrade-to-compile with a metered miss, never crash a "
        "starting worker over a bad cache file",
    ("harp_tpu/parallel/p2p.py", "_reader", "JL105"):
        "an undecodable peer payload (gang version skew) can raise "
        "anything pickle-reachable; the frame boundary is intact, so the "
        "reader logs and survives instead of killing the event plane",
    ("harp_tpu/parallel/failure.py", "_run", "JL105"):
        "the device-probe thread exists to classify ARBITRARY backend "
        "failures on a poisoned device — any exception IS the positive "
        "detection signal, recorded and surfaced to the watchdog",
    ("harp_tpu/utils/checkpoint.py", "verify_step_dir", "JL105"):
        "a corrupt/torn orbax step can fail restore with any backend "
        "error class; verify must report False (skip the step for "
        "resume), never crash the relaunch",
    ("harp_tpu/utils/checkpoint.py", "restore_latest_valid", "JL105"):
        "resume-time payload reads of possibly-corrupt steps: any "
        "load/parse error means 'skip this step and try the previous "
        "one' — crashing here would defeat the elastic-restart journal",
    ("harp_tpu/benchmark/scaling.py", "measure", "JL105"):
        "sweep harness: one failing width config must record its error "
        "string and let the remaining grid points run (bench must not "
        "die mid-sweep)",
    ("harp_tpu/benchmark/serving_load.py", "_client_loop", "JL105"):
        "closed-loop load thread: any per-request failure (ServeError, "
        "timeout, transport reset) must be counted into the row's errors "
        "field and the mix kept running — a dying generator would turn a "
        "server-side error into a missing measurement",
    ("harp_tpu/benchmark/serving_fleet.py", "client_loop", "JL105"):
        "fleet chaos-scenario load threads (recovery/refresh): the row's "
        "ZERO-failures acceptance IS the tally of these catches — any "
        "per-request failure past the retry layer must land in the "
        "errors field, and a dying generator would hide exactly the "
        "failed request the scenario exists to count",
    ("harp_tpu/benchmark/serving_fleet.py", "loop", "JL105"):
        "hot-key pass load thread: same zero-failures tally contract as "
        "client_loop — per-request failures are the measurement, not a "
        "crash",
    ("harp_tpu/benchmark/serving_fleet.py", "load", "JL105"):
        "autoscale-ramp load thread: same zero-failures tally contract "
        "as client_loop — anything past the shed/retry classification "
        "must land in the errors field or the closed loop's join hangs "
        "and the row loses the failed request it exists to count",
    ("harp_tpu/serve/batcher.py", "_dispatch", "JL105"):
        "a malformed query payload in a coalesced serving batch can raise "
        "anything from dtype casts to shape errors deep in the dispatch; "
        "the micro-batcher must reply dispatch-error to the batch and keep "
        "serving live traffic, never die mid-stream",
    ("harp_tpu/serve/batcher.py", "_safe_reply", "JL105"):
        "a reply-path failure (malformed reply_to past the router guard, "
        "transport edge case) must cost exactly one reply, logged and "
        "counted — never the batcher thread or the rest of a served "
        "batch's replies",
    ("harp_tpu/serve/router.py", "_loop", "JL105"):
        "the worker's receive thread is its lifeline: a malformed request "
        "frame (missing id, unhashable model) beyond the typed guards "
        "must cost one dropped frame — logged and counted — never kill "
        "the serving loop and blackhole all subsequent traffic",
    ("harp_tpu/serve/router.py", "_close_at_exit", "JL105"):
        "interpreter-exit cleanup over the live worker/client set: one "
        "wedged close (drain timeout, dead socket) must not skip closing "
        "the remaining objects — each gets its attempt, failures logged",
    ("harp_tpu/sched/dynamic.py", "_monitor", "JL105"):
        "BaseException on purpose: a failing task must still fill its "
        "output slot or consumers block forever in wait_for_output; the "
        "error is re-raised on the CALLER's thread when the slot is "
        "claimed",
}
