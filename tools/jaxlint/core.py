"""jaxlint core — finding model, file walking, and the shared allowlist.

Two engines share this plumbing (ISSUE 5):

* **AST checkers** (``checkers_ast.py``) walk every ``harp_tpu/`` module and
  flag patterns that are invisible until a multi-host run hangs: collectives
  inside rank-conditional branches, unknown collective axis names, retrace
  hazards, host syncs in hot loops, unjustified broad excepts, and hot-path
  scatters (folded in from the r6 ``tools/lint_scatter.py``).
* **jaxpr checkers** (``checkers_jaxpr.py``) trace every model's step
  function with ``jax.make_jaxpr`` (no execution) and pin the traced
  collective counts/kinds to ``tools/collective_budget.json`` plus a
  dtype-policy assert.

Allowlist contract (same rules as the r6 scatter lint, generalized):
entries are keyed by ``(repo-relative file, enclosing function, code)`` and
MUST carry a justification string — the next reader learns why the exemption
is sound. An entry whose key matches no live finding is STALE and fails the
run: exemptions must be pruned when the exempted code is fixed, or they rot
into blanket passes for future regressions in that function.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# Trees the AST engine covers. The scatter checker additionally restricts
# itself to the device-code hot trees (see checkers_ast.HOT_TREES).
SCAN_TREE = "harp_tpu"

AllowKey = Tuple[str, str, str]          # (path, function, code)
Allowlist = Dict[AllowKey, str]          # -> justification (mandatory)

MIN_JUSTIFICATION = 20   # characters; "ok" / "legacy" are not justifications


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: a (file, line, function) anchored rule violation."""

    code: str       # e.g. "JL101"
    checker: str    # e.g. "collective-divergence"
    path: str       # repo-relative, forward slashes
    line: int
    func: str       # enclosing function name, or "<module>"
    message: str

    @property
    def key(self) -> AllowKey:
        return (self.path, self.func, self.code)

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: {self.code}[{self.checker}] in "
                f"{self.func}(): {self.message}")


class FuncStackVisitor(ast.NodeVisitor):
    """Visitor that tracks the enclosing-function stack (checkers subclass
    this; the allowlist is keyed on the innermost enclosing function, the
    same granularity the scatter lint used)."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.func_stack: List[str] = []
        self.findings: List[Finding] = []

    @property
    def func(self) -> str:
        return self.func_stack[-1] if self.func_stack else "<module>"

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        self.enter_function(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def enter_function(self, node) -> None:   # hook for subclasses
        pass

    def emit(self, code: str, checker: str, node: ast.AST, message: str,
             func: Optional[str] = None) -> None:
        self.findings.append(Finding(
            code=code, checker=checker, path=self.rel_path,
            line=getattr(node, "lineno", 0),
            func=self.func if func is None else func, message=message))


def iter_py_files(repo_root: str, tree: str = SCAN_TREE,
                  ) -> Iterable[Tuple[str, str]]:
    """Yield (repo-relative path, source) for every .py under ``tree``."""
    base = os.path.join(repo_root, tree)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            abs_path = os.path.join(dirpath, name)
            rel = os.path.relpath(abs_path, repo_root).replace(os.sep, "/")
            with open(abs_path, encoding="utf-8") as f:
                yield rel, f.read()


CheckerFn = Callable[[ast.AST, str, str], List[Finding]]


def run_ast_checkers(repo_root: str, checkers: Iterable[CheckerFn],
                     tree: str = SCAN_TREE) -> List[Finding]:
    """Raw findings (pre-allowlist) from every checker over every module."""
    out: List[Finding] = []
    parsed = [(rel, src, ast.parse(src, filename=rel))
              for rel, src in iter_py_files(repo_root, tree)]
    for checker in checkers:
        for rel, src, mod in parsed:
            out.extend(checker(mod, rel, src))
    return sorted(out, key=lambda f: (f.path, f.line, f.code))


def validate_allowlist(allowlist: Allowlist) -> List[str]:
    """Schema errors: malformed keys or missing/too-short justifications."""
    errors = []
    for key, why in allowlist.items():
        if (not isinstance(key, tuple) or len(key) != 3
                or not all(isinstance(p, str) for p in key)):
            errors.append(f"allowlist key {key!r} is not a "
                          f"(file, function, code) string triple")
            continue
        if not isinstance(why, str) or len(why.strip()) < MIN_JUSTIFICATION:
            errors.append(
                f"allowlist entry {key[0]}::{key[1]}::{key[2]} needs a real "
                f"justification (>= {MIN_JUSTIFICATION} chars), got "
                f"{why!r}")
    return errors


# one allowlist file, one pool PER ENGINE: traced findings of the memory
# engine (JL4xx) and the lowered-HLO engine (JL5xx) key on the budget file
# + target name, everything else keys on source locations the AST engines
# own. Each pass applies ONLY its pool — a cross-engine entry must never
# report stale just because the pass that owns it didn't run.
ENGINE_CODE_PREFIXES = {"memory": ("JL4",), "hlo": ("JL5",)}


def split_allowlist(allowlist: Allowlist) -> Dict[str, Allowlist]:
    """``{"ast": ..., "memory": ..., "hlo": ...}`` — a disjoint,
    exhaustive partition of the allowlist by owning engine (malformed keys
    land in the ast pool, where validate_allowlist already reports
    them)."""
    pools: Dict[str, Allowlist] = {name: {}
                                   for name in ("ast", *ENGINE_CODE_PREFIXES)}
    for key, why in allowlist.items():
        code = key[2] if (isinstance(key, tuple) and len(key) == 3
                          and isinstance(key[2], str)) else ""
        for engine, prefixes in ENGINE_CODE_PREFIXES.items():
            if code.startswith(prefixes):
                pools[engine][key] = why
                break
        else:
            pools["ast"][key] = why
    return pools


def apply_allowlist(raw: List[Finding], allowlist: Allowlist,
                    ) -> Tuple[List[Finding], List[str]]:
    """Split raw findings into (active, stale-entry errors).

    A finding whose (path, func, code) is allowlisted is suppressed; an
    allowlist entry matching NO raw finding is stale and reported — the
    exempted code was fixed, so the exemption must be pruned (otherwise it
    silently pre-approves the next violation in that function).
    """
    # malformed keys are reported by validate_allowlist; skip them here so
    # one bad entry can't crash the run and hide every other finding
    wellformed = {k for k in allowlist
                  if isinstance(k, tuple) and len(k) == 3
                  and all(isinstance(p, str) for p in k)}
    live_keys = {f.key for f in raw}
    active = [f for f in raw if f.key not in wellformed]
    stale = [f"stale allowlist entry (no {code} finding in {path}::{func} "
             f"anymore — prune it)"
             for (path, func, code) in sorted(wellformed)
             if (path, func, code) not in live_keys]
    return active, stale
