#!/usr/bin/env python
"""AOT artifact round-trip smoke (ISSUE 15 satellite — ci_checks stage 7).

One bounded, self-contained pass over the whole artifact story:

  1. EXPORT   — the manifest registry's serving models export every
               (model, bucket) resident dispatch into a temp store;
  2. HASH     — the freshly exported content hashes must match the
               committed ``tools/artifact_manifest.json`` (the jaxlint
               gate, re-asserted here so this stage is self-sufficient);
  3. LOAD     — FRESH endpoints (same deterministic specs) install every
               artifact; all buckets must hit, none may trace
               (``trace_counts`` stays empty — the never-recompile
               contract);
  4. PARITY   — for real query batches, the loaded dispatch must answer
               bit-identically to the freshly compiled donor dispatch.

Exit nonzero on any failure. Usage: ``python -m tools.aot_roundtrip_smoke``.
"""

from __future__ import annotations

import os
import sys
import tempfile


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.jaxlint.trace_targets import ensure_cpu_mesh

    ensure_cpu_mesh()
    import numpy as np

    from harp_tpu.aot import manifest, serve_artifacts
    from harp_tpu.aot.store import ArtifactStore
    from harp_tpu.serve import fleet as fleet_mod
    from harp_tpu.utils.metrics import Metrics

    failures = []
    metrics = Metrics()
    workdir = tempfile.mkdtemp(prefix="harp-aot-smoke-")
    store = ArtifactStore(workdir, metrics=metrics)

    # 1-2. export + hash-check against the committed manifest
    findings = manifest.check(root, workdir)
    for f in findings:
        failures.append(f"hash-check: {f}")
    print(f"aot smoke: manifest hash-check — {len(findings)} finding(s)")

    # 3-4. load into fresh endpoints, zero-trace + serve parity
    sess = manifest._session()
    rng = np.random.default_rng(20)
    for model, mspec in manifest.SERVE_MODELS.items():
        # the donor compiles fresh (the parity reference); the twin loads
        # the artifacts manifest.check already exported into this same
        # workdir — no second export of identical programs
        donor = fleet_mod.build_endpoint(sess, model, mspec)
        twin = fleet_mod.build_endpoint(sess, model, mspec)
        loaded = serve_artifacts.load_endpoint(
            store, twin,
            model_hash=serve_artifacts.model_hash_from_spec(mspec))
        if loaded != sorted(donor.bucket_sizes):
            failures.append(f"{model}: loaded {loaded}, wanted every "
                            f"bucket {sorted(donor.bucket_sizes)}")
            continue
        for n in (1, donor.bucket_sizes[0]):
            if mspec["kind"] == "topk":
                batch = rng.integers(0, int(mspec["num_users"]), size=n)
            else:
                batch = rng.normal(size=(n, int(mspec["dim"]))).astype(
                    np.float32)
            got, want = twin.dispatch(batch), donor.dispatch(batch)
            if got != want:
                failures.append(f"{model} n={n}: loaded dispatch diverged "
                                f"from compiled: {got[:1]} vs {want[:1]}")
        if twin.trace_counts:
            failures.append(f"{model}: artifact-loaded endpoint TRACED "
                            f"{twin.trace_counts} — the load silently "
                            f"fell back to compile")
        print(f"aot smoke: {model} — {len(loaded)} bucket(s) loaded, "
              f"parity checked, trace_counts={twin.trace_counts}")

    counters = metrics.snapshot()["counters"]
    misses = {k: v for k, v in counters.items()
              if k.startswith("aot.store.miss_")}
    if misses:
        failures.append(f"unexpected store misses in a same-process "
                        f"round trip: {misses}")
    if failures:
        for f in failures:
            print(f"aot smoke FAIL: {f}", file=sys.stderr)
        return 1
    print(f"aot smoke: round trip clean "
          f"(hits={int(counters.get('aot.store.hit', 0))})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
