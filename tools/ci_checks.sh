#!/usr/bin/env bash
# One-exit-code CI gate for harp_tpu (ISSUE 5 satellite):
#
#   1. jaxlint      — AST + jaxpr static analysis (collective divergence,
#                     axis names, retrace hazards, host syncs, broad
#                     excepts, scatters, collective-budget pinning, dtype
#                     policy, and JL203 byte budgets: per-step collective
#                     operand BYTES incl. the quantized trace targets — a
#                     quantized path silently reverting to f32 fails here.
#                     r10: the manifest also pins fused ring-DMA targets
#                     (lda_cgs_fused, sgd_mf_dense_fused, and the
#                     quantized-wt lda_cgs_quantwt_int8): their rotation
#                     hops are booked as the `fused_dma` kind with explicit
#                     fused_dma_bytes_per_step rows, so a fused schedule
#                     silently reverting to bare ppermute moves bytes
#                     between kinds and fails here too.
#                     r11: the manifest also pins the ONLINE-SERVING
#                     dispatch programs (harp_tpu/serve/):
#                     serve_classify_nn at ZERO collectives and
#                     serve_topk_mf at exactly the keyval-lookup
#                     all_to_all x3 + overflow psum — a collective
#                     sneaking into the resident predict dispatch, or its
#                     bytes growing, fails JL201/JL203; the one-compile-
#                     per-(model,bucket) retrace contract is asserted by
#                     tests/test_serve.py in stage 5.
#                     r12: the manifest also pins the ON-DEVICE RESHARD
#                     step programs (collectives/reshard.py):
#                     reshard_factor_a2a at ONE all_to_all whose operand
#                     bytes ARE the per-round chunk budget (512 B at the
#                     traced shape), reshard_factor_ring at the per-shift
#                     ppermute schedule, and serve_topk_mf_rebalanced at
#                     the SAME 3 all_to_alls as serve_topk_mf — a reshard
#                     schedule silently degrading toward a full gather,
#                     or a rebalance adding a collective to the request
#                     path, fails JL201/JL203; bitwise parity vs the
#                     numpy oracle is asserted by tests/test_reshard.py
#                     in stage 5);
#                     nonzero on any finding or stale allowlist entry.
#                     r13 (ISSUE 13): stage 1 also runs the JL3xx
#                     CONCURRENCY engine (checkers_threads.py) over the
#                     threaded host plane (serve/, telemetry/, parallel/,
#                     sched/): unguarded shared writes (JL301),
#                     unsynchronized read-modify-writes (JL302), lock-order
#                     inversions (JL303), and thread-lifecycle hygiene
#                     (JL304) — the hand-review race class of PRs 10-12 is
#                     now a lint, with every benign exception individually
#                     justified in the allowlist.
#   2. telemetry    — the jaxpr engine re-run with the gang telemetry layer
#                     ENABLED (HARP_TELEMETRY_DIR set): the instrumented
#                     step programs must reproduce the pinned manifest
#                     exactly — telemetry is host-boundary-only by design,
#                     and this gate makes that a checked contract, not a
#                     comment (ISSUE 7). r13: the same invocation also sets
#                     HARP_TRACE_REQUESTS=1, extending the zero-drift gate
#                     to the serving observability plane — request tracing
#                     stamps host boundaries in the serve router/batcher,
#                     so the serve_* dispatch targets (and everything else)
#                     must stay byte-identical with per-request spans on.
#                     The exporter /metrics//snapshot//gang schema smoke
#                     and the watchdog/skew/span tests ride stage 5
#                     (tests/test_serve_observability.py).
#   3. gang budgets — the jaxpr engine's GANG MODE only (ISSUE 13, the
#                     carried "jaxlint multi-host budgets" item): the
#                     dryrun_multichip step programs traced on the virtual
#                     2-host x 4-device mesh with the workers axis hinted
#                     DCN, pinned per target as collective counts,
#                     per-process shard shapes, and bytes_by_kind split by
#                     LINK CLASS (DCN vs ICI, mesh.axis_link_class) — a
#                     gang program whose DCN bytes grow, or whose
#                     per-process shard shape drifts, fails JL203/JL201
#                     exactly like the single-process targets.
#   4. check_claims — README/PERF headline numbers vs BENCH_local.json.
#   5. tier-1       — the ROADMAP.md verify suite (which itself re-runs
#                     jaxlint's clean-repo + budget checks as tests, so
#                     DOTS_PASSED captures them).
#   7. aot round-trip — ISSUE 15: the compiled-program artifact story
#                     end to end (tools/aot_roundtrip_smoke.py): export
#                     the registry's serving dispatches → content hashes
#                     must match the pinned tools/artifact_manifest.json
#                     (also checked inside stage 1's full jaxlint run:
#                     a silently changed compiled program is a finding,
#                     `python -m tools.jaxlint --update-artifacts`
#                     regenerates deliberately) → load into FRESH
#                     endpoints (every bucket hits, trace_counts stays 0
#                     — the never-recompile contract) → loaded dispatch
#                     answers bit-identically to the freshly compiled
#                     one.
#   6. serving chaos — ISSUE 14: a scripted kill-under-load on the
#                     in-process serving gang (HARP_FAULT=kill@request=N
#                     through the serving fault grammar): the LocalFleet
#                     supervisor must replace the dead worker, restore
#                     its shard through the on-device reshard engine,
#                     re-route the placement, and the retrying client
#                     must lose ZERO requests. Note the serve_* trace
#                     targets are re-verified byte-identical with the
#                     versioned-swap (push_epoch) code in place by
#                     stages 1-2: version state is host-side only and
#                     never enters a traced dispatch.
#   8. overload chaos — ISSUE 16: the overload-resilient serving story
#                     end to end (tools/overload_chaos_smoke.py): a QPS
#                     ramp with scripted wire faults (netdrop) AND a
#                     scripted kill, while the demand-driven autoscaler
#                     grows/shrinks the fleet through the versioned-
#                     placement push — every request answered correctly
#                     or cleanly shed with a retryable ``overloaded``
#                     reply (0 failed / 0 wrong / 0 hung), worker count
#                     follows the ramp up AND down, the kill recovers
#                     mid-storm, and fresh workers install untraced
#                     (trace_counts 0) behind a versioned placement.
#
# r18 (ISSUE 17): stage 1's manifest additionally pins the QUANTIZED
# serving dispatch — serve_topk_mf_int8 at the SAME 3 all_to_alls +
# overflow psum as serve_topk_mf but 172 B/step vs 356 B (the packed
# int8 rows ride the route/route-back wire): an int8 endpoint silently
# reverting to f32 payloads re-widens the wire at unchanged counts,
# which is exactly the JL203 byte-drift signature (tier-1 doctors one in
# tests/test_serve_quant.py to prove the gate fires, and stage 4 pins
# the same bytes — plus the committed serving_quant resident-reduction/
# overlap row — into the PERF.md/README prose). The int8 scoring dot
# accumulates in int32 via preferred_element_type, which the JL202 dtype
# policy accepts by construction (it flags bf16-accumulating dots, not
# integer dots).
#
#   10. memory budgets — ISSUE 19 (r20): the STATIC MEMORY engine (JL4xx,
#                     tools/jaxlint/checkers_memory.py) as its own
#                     attributable stage: liveness analysis over every
#                     traced program in BOTH registries pins per-target
#                     resident_arg_bytes / peak_live_bytes /
#                     transient_peak_ratio rows in the manifest's `memory`
#                     section (JL401 — drift fails exactly like JL203
#                     byte-drift; a grown static peak is the OOM that
#                     would otherwise ship invisibly, a grown resident set
#                     changes what the model mall can co-locate), audits
#                     every donate_argnums buffer for provable
#                     output aliasing (JL402 — XLA drops a mismatched
#                     donation with only a warning, doubling the buffer
#                     the caller believes is reused), flags closed-over
#                     constants ≥ 64 KiB baked into jaxprs (JL403), and
#                     flags any program whose liveness peak exceeds 20x
#                     its resident argument bytes (JL404 — the static
#                     signature of an accidental full gather/broadcast
#                     materialization). Stages 1-2 already run the engine
#                     inside their full/telemetry passes; this pass gives
#                     memory-budget failures their own CI banner. The same
#                     static rows ride each AOT artifact's meta (store
#                     metadata, never a key axis) and are cross-checked
#                     against Endpoint.resident_bytes() in tier-1.
#
#   9. ingest smoke — ISSUE 18: the streaming ingestion engine end to end
#                     (tools/ingest_smoke.py): part-files through the
#                     bounded reader pool must reproduce the in-memory
#                     load row for row; the stream-fed
#                     KMeans.fit_from_stream (through the DevicePrefetcher
#                     H2D thread) must match the in-memory fit BITWISE;
#                     and the device COO regroup on the jaxlint-pinned
#                     ingest_coo_regroup bounded all_to_all schedule (480
#                     B/step at the traced shape — degrading toward a full
#                     gather fails stage 1's JL203) must match the
#                     host-shuffle oracle nnz for nnz, with the
#                     distributed COO→CSR matching the per-block
#                     counting-sort oracle exactly.
#
#   11. hlo gate   — ISSUE 20 (r21): the LOWERED-HLO engine (JL5xx,
#                     tools/jaxlint/checkers_hlo.py) as its own
#                     attributable stage: every cached trace target in
#                     BOTH registries is compiled post-SPMD
#                     (jax.jit(...).lower().compile() — compilation only,
#                     nothing executes) and the optimized HLO is parsed
#                     for what the PARTITIONER actually emitted. A
#                     compiled collective kind no traced primitive maps
#                     to is a JL501 finding (GSPMD inserted communication
#                     after tracing — the layer every jaxpr-pinned byte
#                     budget is blind to), per-target compiled cost rows
#                     (collective counts + result bytes, instruction
#                     count, while count) are pinned in the manifest's
#                     `hlo` section (JL502 — drift fails exactly like
#                     JL203), an operand declared sharded that compiled
#                     REPLICATED is a JL503 finding (the static signature
#                     of a silent full broadcast), and the 6 pinned
#                     serving dispatches are lowered per reachable device
#                     kind into the `device_kinds` matrix (JL504 — cpu in
#                     CI; TPU kinds pin when lint runs there and are
#                     carried forward, never stale, by CPU regenerates).
#                     Stage 1 already runs the engine inside its full
#                     pass; this pass gives compiled-contract failures
#                     their own CI banner. The same hlo rows ride each
#                     AOT artifact's meta (store metadata, never a key
#                     axis), and stage 4 pins the PERF.md r21
#                     compiled-collective table against the manifest at
#                     tol 0.
#
# Any stage failing fails the script; all stages always run (a lint
# finding must not hide a test regression or vice versa).

set -u
cd "$(dirname "$0")/.."
rc=0

echo "== [1/11] jaxlint (AST + JL3xx concurrency + jaxpr + gang budgets + artifact manifest) =="
python -m tools.jaxlint || rc=1

echo "== [2/11] jaxlint budget with telemetry + request tracing ON (zero drift) =="
tele_dir="$(mktemp -d /tmp/_tele_gate.XXXXXX)"
HARP_TELEMETRY_DIR="$tele_dir" HARP_TRACE_REQUESTS=1 \
    python -m tools.jaxlint --jaxpr-only || rc=1

echo "== [3/11] gang-mode collective budgets (virtual multi-process mesh) =="
# ISSUE 13: the dryrun_multichip gang-mode step programs traced on the
# virtual 2-host x 4-device mesh with the workers axis hinted DCN —
# counts, per-process shard shapes, and the DCN/ICI link-class byte split
# all pinned against tools/collective_budget.json's gang_targets rows
# (JL201/JL203). --update-budget regenerates the gang rows with the rest.
# Stages 1 and 2 DO already trace the gang registry; this dedicated pass
# (4 targets, seconds) exists so a gang-budget failure is attributable to
# its own stage banner in CI output instead of buried in stage 1's.
python -m tools.jaxlint --gang-only || rc=1

echo "== [4/11] check_claims =="
python tools/check_claims.py || rc=1

echo "== [5/11] tier-1 tests =="
set -o pipefail
t1_log="$(mktemp /tmp/_t1.XXXXXX.log)"   # unique per run: concurrent CI
trap 'rm -f "$t1_log"; rm -rf "$tele_dir"' EXIT   # must not clobber the count
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$t1_log" || rc=1
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$t1_log" \
    | tr -cd . | wc -c)"

echo "== [6/11] serving-chaos smoke (scripted kill under load, zero failures) =="
# bounded like stage 5: a wedged recovery (the exact machinery this smoke
# exercises) must fail CI, never hang it
timeout -k 10 300 python -m tools.serving_chaos_smoke || rc=1

echo "== [7/11] aot artifact round-trip (export -> hash-check -> load -> parity) =="
timeout -k 10 300 python -m tools.aot_roundtrip_smoke || rc=1

echo "== [8/11] overload + network chaos smoke (QPS ramp + netdrop + kill, autoscale up/down, zero failures) =="
timeout -k 10 300 python -m tools.overload_chaos_smoke || rc=1

echo "== [9/11] streaming-ingestion smoke (chunk stream, stream-vs-memory bitwise fit, device COO regroup) =="
timeout -k 10 300 python -m tools.ingest_smoke || rc=1

echo "== [10/11] static memory budgets (JL4xx: liveness rows vs manifest, donation audit, const bloat, transient blowup) =="
# ISSUE 19: stages 1-2 already run the memory engine inside their full/
# telemetry passes; this dedicated pass (analysis over cached traces,
# seconds) exists so a memory-budget failure is attributable to its own
# stage banner in CI output instead of buried in stage 1's.
python -m tools.jaxlint --memory-only || rc=1

echo "== [11/11] lowered-HLO gate (JL5xx: compiler-inserted collectives, pinned hlo rows, sharding propagation, device-kind matrix) =="
# ISSUE 20: stage 1 already runs the hlo engine inside its full pass; this
# dedicated pass (lowering over cached traces, ~30s) exists so a
# compiled-contract failure is attributable to its own stage banner in CI
# output instead of buried in stage 1's.
python -m tools.jaxlint --hlo-only || rc=1

if [ "$rc" -ne 0 ]; then
    echo "ci_checks: FAILED"
else
    echo "ci_checks: all stages passed"
fi
exit $rc
