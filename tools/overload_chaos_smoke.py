"""CI overload + network chaos smoke (ISSUE 16): a QPS ramp against a
one-worker gang with scripted wire faults (``netdrop``) and a scripted
worker kill, while the demand-driven autoscaler grows and shrinks the
fleet underneath the traffic.

The contract asserted here is the overload-resilient serving story end to
end:

* every request is answered CORRECTLY or cleanly shed with a retryable
  ``overloaded`` reply — zero failed, zero wrong, zero hung;
* the autoscaler's trajectory follows the ramp UP (a scale-up journaled
  with its pushed placement version and the fresh endpoints' zero trace
  counts) and back DOWN once the ramp subsides;
* the scripted kill rides the same storm: the fleet supervisor replaces
  the corpse and restores its shards through the reshard engine with the
  retry layer hiding all of it;
* the dropped frames are survived by the client retry contract (the
  retry counter is asserted — a run where nothing retried did not test
  the seam).

Exit 0 = contract held. Run: ``python -m tools.overload_chaos_smoke``
(stage 8 of ci_checks.sh).
"""

from __future__ import annotations

import os
import sys
import threading
import time


def main() -> int:
    from tools.jaxlint.trace_targets import ensure_cpu_mesh

    ensure_cpu_mesh()
    import numpy as np

    from harp_tpu.serve import OP_TOPK, protocol
    from harp_tpu.serve import fleet as fleet_mod
    from harp_tpu.serve.autoscaler import Autoscaler
    from harp_tpu.serve.router import local_gang
    from harp_tpu.session import HarpSession
    from harp_tpu.utils.metrics import DEFAULT as metrics

    sess = HarpSession(num_workers=8)
    specs = {f"m{i}": {"kind": "topk", "num_users": 32, "num_items": 16,
                       "rank": 4, "k": 3, "seed": i} for i in range(3)}
    eps = {name: fleet_mod.build_endpoint(sess, name, sp)
           for name, sp in specs.items()}
    workers, mk = local_gang(sess, [eps], max_wait_s=0.005, max_queue=48,
                             client_rank_base=1000)

    def builder(name, version):
        return fleet_mod.build_endpoint(sess, name, specs[name],
                                        version=version, restore=True)

    canonical = {name: (lambda v, sp=sp: fleet_mod.topk_factors(sp, v)[0])
                 for name, sp in specs.items()}
    fleet = fleet_mod.LocalFleet(workers, mk, canonical=canonical,
                                 endpoint_builder=builder, metrics=metrics)
    refs = {}
    for name, sp in specs.items():
        uf, vf = fleet_mod.topk_factors(sp, 0)
        refs[name] = fleet_mod.topk_reference(uf, vf, sp["k"])

    failures = []
    shed = [0]
    served = [0]
    tally_lock = threading.Lock()
    stop = threading.Event()

    def load(tid: int) -> None:
        c = fleet.make_client()
        rng = np.random.default_rng(tid)
        try:
            while not stop.is_set():
                name = f"m{rng.integers(0, len(specs))}"
                u = int(rng.integers(0, 32))
                try:
                    r = c.request_retry(OP_TOPK, name, u, timeout=10.0,
                                        attempts=10, backoff_max_s=0.5,
                                        sync_timeout=2.0)
                    with tally_lock:
                        served[0] += 1
                    if r["items"] != refs[name][u]:
                        failures.append((tid, name, u, "wrong", r["items"]))
                except protocol.ServeError as e:
                    # a shed that survived the whole retry budget is a
                    # CLEAN outcome (retryable reply, client chose to give
                    # up) — anything else server-reported is a failure
                    if str(e).startswith(protocol.ERR_OVERLOADED):
                        with tally_lock:
                            shed[0] += 1
                    else:
                        failures.append((tid, name, u, repr(e)))
                except Exception as e:  # noqa: BLE001 — tally IS the gate
                    failures.append((tid, name, u, repr(e)))
        finally:
            c.close()

    asc = Autoscaler(fleet, metrics=metrics, poll_interval_s=0.05,
                     up_depth=6.0, down_depth=0.5, up_streak=2,
                     down_streak=10, cooldown_s=0.5, max_workers=3,
                     models_per_move=1)
    threads = [threading.Thread(target=load, args=(i,)) for i in range(10)]
    try:
        # warm every model's dispatch before the chaos arms
        warm = fleet.make_client()
        for name in specs:
            warm.request_retry(OP_TOPK, name, 0, timeout=60.0)
        warm.close()
        # the storm: from here on frames get eaten and rank 0 dies at its
        # 60th request, all while the ramp drives the autoscaler
        os.environ["HARP_FAULT"] = \
            "netdrop@request=40,kill@request=60:rank=0"
        for t in threads:
            t.start()
        peak, t0 = 1, time.monotonic()
        while time.monotonic() - t0 < 30.0:
            peak = max(peak, fleet.worker_count())
            if peak >= 2 and time.monotonic() - t0 >= 8.0:
                break
            time.sleep(0.05)
        stop.set()
        hung = []
        for t in threads:
            t.join(30.0)
            if t.is_alive():
                hung.append(t.name)
        # ramp over: the controller must unwind the shape it built
        t1 = time.monotonic()
        while time.monotonic() - t1 < 30.0 and fleet.worker_count() > 1:
            time.sleep(0.1)
        t2 = time.monotonic()
        while (time.monotonic() - t2 < 10.0
               and not any(r["action"] == "scale-down"
                           for r in asc.trajectory())):
            time.sleep(0.05)
    finally:
        os.environ.pop("HARP_FAULT", None)
        stop.set()
        asc.close()
    events = [r["event"] for r in fleet.journal.records]
    acts = [r.get("action") for r in fleet.journal.records
            if r["event"] == "autoscale-decision"]
    final = fleet.worker_count()
    fleet.close()
    if failures:
        print(f"overload_chaos_smoke: FAILED — {len(failures)} failed/"
              f"wrong request(s): {failures[:5]}")
        return 1
    if hung:
        print(f"overload_chaos_smoke: FAILED — hung load threads: {hung}")
        return 1
    if peak < 2 or final != 1:
        print(f"overload_chaos_smoke: FAILED — worker count did not follow "
              f"the ramp (peak {peak}, final {final}; decisions {acts})")
        return 1
    if "scale-up" not in acts or "scale-down" not in acts:
        print(f"overload_chaos_smoke: FAILED — trajectory missing a move "
              f"({acts})")
        return 1
    if "worker-death" not in events or "replaced" not in events:
        print(f"overload_chaos_smoke: FAILED — the scripted kill did not "
              f"recover (journal: {events})")
        return 1
    up = next(r for r in fleet.journal.records if r["event"] == "scale-up")
    if any(v != 0 for v in up["trace_counts"].values()) \
            or not up.get("placement_version"):
        print(f"overload_chaos_smoke: FAILED — scale-up record malformed "
              f"(fresh worker must start untraced, placement versioned): "
              f"{up}")
        return 1
    retries = metrics.counters.get("serve.client_retries", 0)
    if retries < 1:
        print("overload_chaos_smoke: FAILED — nothing retried: the wire "
              "faults/kill cannot have fired")
        return 1
    print(f"overload_chaos_smoke: OK — {served[0]} served correctly, "
          f"{shed[0]} cleanly shed, 0 failed/wrong/hung across a QPS ramp "
          f"with netdrop + a scripted kill; workers 1 -> {peak} -> {final} "
          f"({retries:.0f} client retries, journal: {events})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
