"""CI serving-chaos smoke (ISSUE 14 satellite): scripted kill-under-load
on the in-process serving gang, asserting ZERO failed requests after the
retry layer rides out the recovery.

The scenario is entirely grammar-driven — ``HARP_FAULT=kill@request=N``
kills serving rank 0 abruptly mid-traffic (transport torn down, in-flight
requests dropped), the LocalFleet supervisor replaces the worker, restores
the top-k shard through the on-device reshard engine, pushes the versioned
placement, and the retrying client must lose NOTHING and read only
correct answers. Exit 0 = contract held; any failed or wrong request, or
a missing journal step, is a non-zero exit for ci_checks.sh.

Run: ``python -m tools.serving_chaos_smoke`` (stage 6 of ci_checks.sh).
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    from tools.jaxlint.trace_targets import ensure_cpu_mesh

    ensure_cpu_mesh()
    import numpy as np

    from harp_tpu.serve import OP_TOPK, TopKEndpoint, local_gang
    from harp_tpu.serve import fleet as fleet_mod
    from harp_tpu.session import HarpSession

    sess = HarpSession(num_workers=8)
    rng = np.random.default_rng(0)
    uf = rng.normal(size=(64, 8)).astype(np.float32)
    items = rng.normal(size=(32, 8)).astype(np.float32)
    ref = fleet_mod.topk_reference(uf, items, 3)
    ep = TopKEndpoint(sess, "mf", uf, items, k=3)
    workers, make_client = local_gang(sess, [{"mf": ep}, {}])
    fleet = fleet_mod.LocalFleet(workers, make_client,
                                 canonical={"mf": uf})
    client = fleet.make_client()
    failures = []
    try:
        # warm the dispatch, then arm the scripted kill mid-traffic
        client.request_retry(OP_TOPK, "mf", 0, timeout=60.0)
        os.environ["HARP_FAULT"] = "kill@request=10:rank=0"
        try:
            for i in range(50):
                u = i % 64
                try:
                    res = client.request_retry(
                        OP_TOPK, "mf", u, timeout=10.0, attempts=10,
                        backoff_max_s=0.5, sync_timeout=2.0)
                    if res["items"] != ref[u]:
                        failures.append((u, "wrong", res["items"]))
                except Exception as e:  # noqa: BLE001 — the tally IS the gate
                    failures.append((u, type(e).__name__, str(e)[:120]))
        finally:
            os.environ.pop("HARP_FAULT", None)
        events = [r["event"] for r in fleet.journal.records]
        if failures:
            print(f"serving_chaos_smoke: FAILED — {len(failures)} "
                  f"failed/wrong request(s): {failures[:5]}")
            return 1
        if "worker-death" not in events or "replaced" not in events:
            print(f"serving_chaos_smoke: FAILED — recovery did not run "
                  f"(journal: {events}); was the kill injected?")
            return 1
        replaced = next(r for r in fleet.journal.records
                        if r["event"] == "replaced")
        if replaced.get("restored_rows", {}).get("mf") != len(uf):
            print(f"serving_chaos_smoke: FAILED — shard restore did not "
                  f"run through the engine: {replaced}")
            return 1
        print(f"serving_chaos_smoke: OK — 50/50 requests answered "
              f"correctly across a scripted worker kill (journal: "
              f"{events}, restored {replaced['restored_rows']['mf']} "
              f"rows, placement v{replaced['placement_version']})")
        return 0
    finally:
        client.close()
        fleet.close()


if __name__ == "__main__":
    sys.exit(main())
