#!/usr/bin/env python
"""Streaming-ingestion smoke (ISSUE 18 satellite — ci_checks stage 9).

One bounded, self-contained pass over the ingestion engine's contracts:

  1. STREAM   — synthetic part-files (ragged sizes on purpose) through the
               bounded reader pool; the chunk sequence must cover every row
               in path order at the fixed budget shape;
  2. PARITY   — ``KMeans.fit_from_stream`` fed through a
               ``DevicePrefetcher`` must produce BITWISE-identical
               centroids and costs to ``fit`` on the same rows loaded in
               memory (the assemble_stream placement contract);
  3. REGROUP  — the device COO regroup (the jaxlint-pinned
               ``ingest_coo_regroup`` bounded all_to_all schedule) must
               match the host-shuffle oracle nnz for nnz, and the
               distributed COO→CSR must match the per-block counting-sort
               oracle exactly.

Exit nonzero on any failure. Usage: ``python -m tools.ingest_smoke``.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.jaxlint.trace_targets import ensure_cpu_mesh

    ensure_cpu_mesh()
    import numpy as np

    from harp_tpu.io import loaders, pipeline as pl
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    w = sess.num_workers
    rng = np.random.default_rng(1800)
    tmp = tempfile.mkdtemp(prefix="harp_ingest_smoke_")
    try:
        # 1. STREAM ---------------------------------------------------------
        sizes, d = [53, 7, 64, 20], 6
        for i, n in enumerate(sizes):
            np.savetxt(os.path.join(tmp, f"part-{i:03d}"),
                       rng.standard_normal((n, d)).astype(np.float32),
                       fmt="%.6f", delimiter=",")
        paths = loaders.list_files(tmp)
        whole = loaders.load_dense_csv(paths)
        chunks = list(pl.StreamLoader(paths, chunk_rows=32, num_threads=3))
        assert all(c.data.shape == (32, d) for c in chunks), "budget shape"
        flat = np.concatenate([c.data[: c.rows] for c in chunks])
        assert np.array_equal(flat, whole), "stream coverage/order"
        print(f"ingest_smoke: stream ok ({len(chunks)} chunks, "
              f"{len(whole)} rows)")

        # 2. PARITY ---------------------------------------------------------
        pts = loaders.truncate_to_workers(whole, w)
        cen0 = whole[:4].copy()
        model = km.KMeans(sess, km.KMeansConfig(
            num_centroids=4, dim=d, iterations=3))
        ref_cen, ref_costs = model.fit(pts, cen0)
        cen, costs = model.fit_from_stream(
            pl.DevicePrefetcher(
                pl.StreamLoader(paths, chunk_rows=32, num_threads=3),
                sess.replicate_put),
            cen0, len(pts))
        assert np.array_equal(np.asarray(cen), np.asarray(ref_cen)), \
            "stream-fed centroids not bitwise-equal to in-memory fit"
        assert np.array_equal(np.asarray(costs), np.asarray(ref_costs)), \
            "stream-fed costs not bitwise-equal to in-memory fit"
        print("ingest_smoke: stream-vs-memory fit bitwise parity ok")

        # 3. REGROUP --------------------------------------------------------
        num_rows, nnz = 101, 5000
        crow = rng.integers(0, num_rows, nnz).astype(np.int64)
        ccol = rng.integers(0, 77, nnz).astype(np.int64)
        cval = rng.standard_normal(nnz).astype(np.float32)
        got = pl.regroup_coo_device(sess, crow, ccol, cval,
                                    num_rows=num_rows)
        block = -(-num_rows // w)
        owner = np.minimum(crow // block, w - 1)
        for wi in range(w):
            m = owner == wi
            assert np.array_equal(got[wi][0], crow[m]) \
                and np.array_equal(got[wi][1], ccol[m]) \
                and np.array_equal(got[wi][2], cval[m]), \
                f"regroup worker {wi} != host oracle"
        csr = pl.coo_to_csr_distributed(sess, crow, ccol, cval,
                                        num_rows=num_rows)
        for wi in range(w):
            lo, hi = wi * block, min((wi + 1) * block, num_rows)
            m = (crow >= lo) & (crow < hi)
            ip, ix, v = loaders.coo_to_csr(crow[m] - lo, ccol[m], cval[m],
                                           num_rows=max(hi - lo, 0))
            assert np.array_equal(csr[wi][0], ip) \
                and np.array_equal(csr[wi][1], ix) \
                and np.array_equal(csr[wi][2], v), \
                f"distributed CSR worker {wi} != per-block oracle"
        print(f"ingest_smoke: device regroup + distributed CSR ok "
              f"({nnz} nnz over {w} workers)")
        print("ingest_smoke: PASS")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
