// Native host-side data loader for harp_tpu.
//
// Reference parity: Harp shipped precompiled native IO helpers (libhdfs.so,
// DAAL's multithreaded CSV/COO readers behind HarpDAALDataSource.java:64 +
// MTReader) because JVM-side parsing was the input-pipeline bottleneck. This is
// the TPU-framework equivalent: an mmap + thread-parallel tokenizer exposed via
// plain C symbols (consumed through ctypes in harp_tpu/io/native_bridge.py — no
// pybind11 dependency).
//
// All functions return -1 / nonzero on error and never throw across the ABI.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct MappedFile {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  char* heap = nullptr;
  size_t mapped = 0;

  // Guarantees a readable NUL terminator after data[size-1]: bytes past EOF up
  // to the page boundary read as zero under POSIX mmap, so only the exact
  // page-multiple case needs a heap copy (strtof/strtoll would otherwise scan
  // into unmapped memory).
  bool open(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < 0) return false;
    size = static_cast<size_t>(st.st_size);
    if (size == 0) {
      data = "";
      return true;
    }
    size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
    if (size % page == 0) {
      heap = static_cast<char*>(malloc(size + 1));
      if (!heap) return false;
      size_t off = 0;
      while (off < size) {
        ssize_t got = ::read(fd, heap + off, size - off);
        if (got <= 0) return false;
        off += static_cast<size_t>(got);
      }
      heap[size] = '\0';
      data = heap;
      return true;
    }
    void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) return false;
    madvise(p, size, MADV_SEQUENTIAL);
    data = static_cast<const char*>(p);
    mapped = size;
    return true;
  }

  ~MappedFile() {
    if (mapped) munmap(const_cast<char*>(data), mapped);
    free(heap);
    if (fd >= 0) close(fd);
  }
};

// Offsets of the first byte of every nonempty line.
std::vector<size_t> line_starts(const char* d, size_t n) {
  std::vector<size_t> starts;
  size_t i = 0;
  while (i < n) {
    while (i < n && (d[i] == '\n' || d[i] == '\r')) i++;
    if (i >= n) break;
    starts.push_back(i);
    const char* nl = static_cast<const char*>(memchr(d + i, '\n', n - i));
    i = nl ? static_cast<size_t>(nl - d) + 1 : n;
  }
  return starts;
}

size_t line_end(const char* d, size_t n, size_t start) {
  const char* nl = static_cast<const char*>(memchr(d + start, '\n', n - start));
  size_t e = nl ? static_cast<size_t>(nl - d) : n;
  while (e > start && (d[e - 1] == '\r' || d[e - 1] == ' ')) e--;
  return e;
}

int64_t count_fields(const char* p, const char* end, char sep) {
  if (p >= end) return 0;
  int64_t k = 1;
  for (; p < end; p++)
    if (*p == sep) k++;
  return k;
}

// Fast decimal float scan for the common CSV shape (sign, digits, optional
// '.digits'): digit accumulation in double is exact to well past float
// precision for <= 17 significant digits. Exponents, inf/nan, hex or
// over-long fields fall back to strtof — identical semantics, just slower.
// Measured r5: strtof was the parse bottleneck (native 110 MB/s on the
// 1-core bench host, BELOW numpy's tokenizer); this path ~3x's it.
inline float scan_float(const char* p, const char* pe, const char** next) {
  const char* q = p;
  while (q < pe && (*q == ' ' || *q == '\t')) q++;  // strtof skips ws too
  bool neg = false;
  if (q < pe && (*q == '-' || *q == '+')) {
    neg = (*q == '-');
    q++;
  }
  double v = 0.0;
  int digits = 0;
  while (q < pe && *q >= '0' && *q <= '9') {
    v = v * 10.0 + (*q - '0');
    digits++;
    q++;
  }
  if (q < pe && *q == '.') {
    q++;
    double scale = 1.0;
    while (q < pe && *q >= '0' && *q <= '9') {
      v = v * 10.0 + (*q - '0');
      scale *= 10.0;
      digits++;
      q++;
    }
    v /= scale;
  }
  if (digits == 0 || digits > 17 ||
      (q < pe && (*q == 'e' || *q == 'E' || *q == 'x' || *q == 'X' ||
                  *q == 'n' || *q == 'N' || *q == 'f' || *q == 'F'))) {
    char* endp = nullptr;
    float f = strtof(p, &endp);
    *next = endp;
    return f;
  }
  *next = q;
  return static_cast<float>(neg ? -v : v);
}

unsigned pick_threads(size_t lines) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  if (hw > 16) hw = 16;
  size_t want = lines / 4096 + 1;
  return static_cast<unsigned>(want < hw ? want : hw);
}

template <typename Fn>
void parallel_lines(const std::vector<size_t>& starts, Fn fn) {
  unsigned nt = pick_threads(starts.size());
  if (nt <= 1) {
    for (size_t i = 0; i < starts.size(); i++) fn(i);
    return;
  }
  std::vector<std::thread> ts;
  size_t per = (starts.size() + nt - 1) / nt;
  for (unsigned t = 0; t < nt; t++) {
    size_t lo = t * per, hi = std::min(starts.size(), lo + per);
    if (lo >= hi) break;
    ts.emplace_back([lo, hi, &fn] {
      for (size_t i = lo; i < hi; i++) fn(i);
    });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Shape probe: sets *rows/*cols from the file; returns rows*cols or -1.
long long harp_count_csv(const char* path, char sep, long long* rows,
                         long long* cols) {
  MappedFile f;
  if (!f.open(path)) return -1;
  auto starts = line_starts(f.data, f.size);
  *rows = static_cast<long long>(starts.size());
  if (starts.empty()) {
    *cols = 0;
    return 0;
  }
  size_t e = line_end(f.data, f.size, starts[0]);
  *cols = count_fields(f.data + starts[0], f.data + e, sep);
  return *rows * *cols;
}

// Parse the whole file as a dense row-major float32 matrix into `out`.
int harp_parse_csv(const char* path, char sep, float* out,
                   long long capacity) {
  MappedFile f;
  if (!f.open(path)) return 1;
  auto starts = line_starts(f.data, f.size);
  if (starts.empty()) return 0;
  size_t e0 = line_end(f.data, f.size, starts[0]);
  int64_t cols = count_fields(f.data + starts[0], f.data + e0, sep);
  if (static_cast<long long>(starts.size()) * cols > capacity) return 2;

  std::vector<int> bad(starts.size(), 0);
  const char* d = f.data;
  size_t n = f.size;
  parallel_lines(starts, [&](size_t i) {
    size_t e = line_end(d, n, starts[i]);
    const char* p = d + starts[i];
    const char* pe = d + e;
    float* row = out + static_cast<int64_t>(i) * cols;
    for (int64_t c = 0; c < cols; c++) {
      if (p >= pe) {  // short row: strtof would scan into the next line
        bad[i] = 1;
        return;
      }
      const char* next = nullptr;
      row[c] = scan_float(p, pe, &next);
      if (next == p || next > pe) {  // unparsable field / number crossed the line
        bad[i] = 1;
        return;
      }
      p = next;
      while (p < pe && (*p == sep || *p == ' ' || *p == '\t')) p++;
    }
    if (p < pe) bad[i] = 1;  // trailing junk → ragged row
  });
  for (int b : bad)
    if (b) return 3;
  return 0;
}

long long harp_count_lines(const char* path) {
  MappedFile f;
  if (!f.open(path)) return -1;
  return static_cast<long long>(line_starts(f.data, f.size).size());
}

// Parse "row col value" whitespace-separated lines.
int harp_parse_coo(const char* path, long long* rows, long long* cols,
                   float* vals, long long n) {
  MappedFile f;
  if (!f.open(path)) return 1;
  auto starts = line_starts(f.data, f.size);
  if (static_cast<long long>(starts.size()) != n) return 2;
  const char* d = f.data;
  size_t sz = f.size;
  std::vector<int> bad(starts.size(), 0);
  parallel_lines(starts, [&](size_t i) {
    const char* pe = d + line_end(d, sz, starts[i]);
    const char* p = d + starts[i];
    char* next = nullptr;
    rows[i] = strtoll(p, &next, 10);
    if (next == p || next > pe) { bad[i] = 1; return; }
    p = next;
    cols[i] = strtoll(p, &next, 10);
    if (next == p || next > pe) { bad[i] = 1; return; }
    p = next;
    const char* vend = nullptr;
    vals[i] = scan_float(p, pe, &vend);
    if (vend == p || vend > pe) { bad[i] = 1; return; }
  });
  for (int b : bad)
    if (b) return 3;
  return 0;
}

// COO→CSR (HarpDAALDataSource.COOToCSR:439 parity): STABLE parallel
// counting sort by row — O(nnz + num_rows) vs numpy's single-threaded
// O(nnz log nnz) argsort. `indptr` needs num_rows+1 slots; `indices`/
// `values_out` need nnz. Stability contract: entries of one row keep their
// input order (duplicate (row, col) semantics depend on it upstream).
// Returns 0 ok, 1 bad args, 4 row id out of [0, num_rows).
int harp_coo_to_csr(const long long* rows, const long long* cols,
                    const float* vals, long long nnz, long long num_rows,
                    long long* indptr, long long* indices,
                    float* values_out) {
  if (nnz < 0 || num_rows < 0) return 1;
  unsigned nt = pick_threads(static_cast<size_t>(nnz / 16 + 1));
  // per-thread histograms cost nt*num_rows slots; keep the table ≤ 64M
  // entries so wide-row inputs do not balloon host memory
  while (nt > 1 &&
         static_cast<long long>(nt) * num_rows > (64LL << 20)) nt--;
  size_t per = static_cast<size_t>((nnz + nt - 1) / nt);
  std::vector<std::vector<long long>> hist(
      nt, std::vector<long long>(static_cast<size_t>(num_rows), 0));
  std::vector<int> bad(nt, 0);
  {
    std::vector<std::thread> ts;
    for (unsigned t = 0; t < nt; t++) {
      size_t lo = t * per;
      size_t hi = std::min(static_cast<size_t>(nnz), lo + per);
      if (lo >= hi) break;
      ts.emplace_back([&, t, lo, hi] {
        auto& h = hist[t];
        for (size_t i = lo; i < hi; i++) {
          long long r = rows[i];
          if (r < 0 || r >= num_rows) { bad[t] = 1; return; }
          h[static_cast<size_t>(r)]++;
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  for (int b : bad)
    if (b) return 4;
  // serial pass: global indptr + per-(row, thread) scatter bases. Thread
  // chunks are consumed in input order, so base ordering = stability.
  long long run = 0;
  for (long long r = 0; r < num_rows; r++) {
    indptr[r] = run;
    for (unsigned t = 0; t < nt; t++) {
      long long c = hist[t][static_cast<size_t>(r)];
      hist[t][static_cast<size_t>(r)] = run;  // becomes this chunk's cursor
      run += c;
    }
  }
  indptr[num_rows] = run;
  {
    std::vector<std::thread> ts;
    for (unsigned t = 0; t < nt; t++) {
      size_t lo = t * per;
      size_t hi = std::min(static_cast<size_t>(nnz), lo + per);
      if (lo >= hi) break;
      ts.emplace_back([&, t, lo, hi] {
        auto& cursor = hist[t];
        for (size_t i = lo; i < hi; i++) {
          long long p = cursor[static_cast<size_t>(rows[i])]++;
          indices[p] = cols[i];
          values_out[p] = vals[i];
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  return 0;
}

}  // extern "C"
