#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Covers the five BASELINE workload configs (BASELINE.json): K-means
regroupallgather (the flagship/primary metric), SGD-MF (rotate pipeline),
PCA/covariance (dense allreduce), CGS-LDA (rotation + blocked sampling), and
mini-batch NN — each anchored against an optimized CPU implementation
(numpy/BLAS — the same linear-algebra core DAAL uses) of the IDENTICAL
workload on this host: the reference publishes no absolute throughput
(BASELINE.md), and the north-star is "match DAAL-on-Xeon iteration
throughput". A subprocess on an 8-device virtual CPU mesh adds the 1→2→4→8
strong-scaling curve and the collective micro-benchmarks
(harp_tpu/benchmark/{scaling,collectives}.py).

Usage: python bench.py [--small]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))


# --------------------------------------------------------------------------- #
# K-means (BASELINE configs[0] — flagship, primary metric)
# --------------------------------------------------------------------------- #

def tpu_kmeans_iters_per_sec(n, k, d, iters, compute_dtype="float32"):
    import jax.numpy as jnp
    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()  # all visible devices (1 real chip under the driver)
    pts = datagen.dense_points(n - n % sess.num_workers or n, d, seed=7,
                               num_clusters=k)
    n_eff = pts.shape[0] - pts.shape[0] % sess.num_workers
    pts = pts[:n_eff]

    model = km.KMeans(sess, km.KMeansConfig(k, d, iters, "regroupallgather",
                                            compute_dtype=compute_dtype))
    pts_dev, cen_dev = model.prepare(pts, datagen.initial_centroids(pts, k, seed=3))
    _, costs = model.fit_prepared(pts_dev, cen_dev)   # compile + warmup
    np.asarray(costs)  # fetch forces execution (block_until_ready is async on
    #                    remote-tunnel platforms)
    best, final_cost = 0.0, 0.0
    for trial in range(3):
        cen_t = sess.replicate_put(
            jnp.asarray(datagen.initial_centroids(pts, k, seed=100 + trial)))
        t0 = time.perf_counter()
        _, costs = model.fit_prepared(pts_dev, cen_t)
        final_cost = float(np.asarray(costs)[-1])
        best = max(best, iters / (time.perf_counter() - t0))
    # HBM roofline view (VERDICT r3 weak #4): the E-step is BANDWIDTH-bound
    # by design (kmeans.py prepare note) — per iteration the point block is
    # read twice (distance GEMM + stats GEMM); centroid/stat traffic is
    # K-sized noise. achieved bytes/s vs the v5e roofline answers "is it
    # actually fast", which vs-one-CPU-core cannot.
    bytes_per_point = 2 if compute_dtype == "bfloat16" else 4
    bytes_per_iter = 2.0 * n_eff * d * bytes_per_point
    hbm_pct = 100.0 * bytes_per_iter * best / (
        V5E_HBM_GBPS * sess.num_workers)
    return best, final_cost, hbm_pct


def cpu_kmeans_iters_per_sec(n, k, d, iters):
    """BLAS-backed Lloyd iteration — the DAAL-equivalent CPU anchor."""
    rng = np.random.default_rng(7)
    pts = rng.random((n, d), dtype=np.float32)
    cen = pts[:k].copy()

    def one_iter(cen):
        x2 = (pts * pts).sum(1, keepdims=True)
        c2 = (cen * cen).sum(1)[None, :]
        dist = x2 - 2.0 * pts @ cen.T + c2
        a = dist.argmin(1)
        oh = np.zeros((n, k), np.float32)
        oh[np.arange(n), a] = 1.0
        sums = oh.T @ pts
        cnt = oh.sum(0)[:, None]
        return sums / np.maximum(cnt, 1.0)

    cen = one_iter(cen)     # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        cen = one_iter(cen)
    return iters / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# SGD-MF (BASELINE configs[2] — rotate pipeline; dense masked-stripe layout)
# --------------------------------------------------------------------------- #

V5E_BF16_PEAK = 197e12   # TPU v5e peak bf16 FLOP/s (MFU denominator)
V5E_HBM_GBPS = 819e9     # TPU v5e HBM bandwidth roofline (bytes/s)

# The DAAL-on-Xeon north star (BASELINE.md): the comparison machine is a
# 2x18-core Haswell E5-2699 v3. This host has exactly ONE (modern Zen) core,
# so a measured multicore anchor is impossible; instead every vs-CPU ratio
# also ships a CONSERVATIVE LOWER BOUND on the vs-Xeon ratio: divide by 36,
# i.e. assume the same BLAS anchor scales PERFECTLY linearly to all 36
# Haswell cores AND that a 2015 Haswell core matches this Zen core per-core.
# Both assumptions favor the Xeon (memory-bound kernels scale sublinearly;
# Haswell is slower per-core), so vs_xeon36_lb >= 1 genuinely supports
# "matches DAAL-on-Xeon throughput".
XEON_CORES = 36


def xeon_lb(vs_cpu: float) -> float:
    return round(vs_cpu / XEON_CORES, 2)


def tpu_sgd_mf_samples_per_sec(nu, ni, epochs, rank=32):
    """Steady-state training throughput: epochs loop inside ONE compiled
    program, timed via train_prepared (rmse-only fetch — the final-model D2H
    is a one-time cost, not part of per-epoch throughput; round 2 measured
    it by accident, see PERF.md r3)."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sgd_mf
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    cfg = sgd_mf.SGDMFConfig(rank=rank, lam=0.01, lr=0.05, epochs=epochs,
                             minibatches_per_hop=8)
    model = sgd_mf.SGDMF(sess, cfg)
    state = model.prepare(rows, cols, vals, nu, ni)
    nnz = len(vals) - model.last_layout_stats.get("duplicates_dropped", 0)
    model.train_prepared(state)                  # compile + warm-up
    best, rmse_last = 0.0, 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        _, _, rmse = model.train_prepared(state)
        dt = time.perf_counter() - t0
        best = max(best, nnz * epochs / dt)
        rmse_last = float(rmse[-1])
    layout = model.last_layout_stats["layout"]
    # two utilization views (VERDICT r3 weak #3 — one number conflated them):
    # mxu_busy: the three dense slab GEMMs the program actually issues (the
    #   dense layout computes on NaN holes by design — this measures how
    #   hard the MXU runs, not algorithmic efficiency);
    # nnz_mfu: only the 6*nnz*rank flops a sparse-exact algorithm needs —
    #   the honest algorithmic-efficiency number (~density * mxu_busy)
    epochs_per_sec = best / nnz
    mxu_busy = (6.0 * nu * ni * rank * epochs_per_sec
                / (V5E_BF16_PEAK * sess.num_workers)
                if layout == "dense" else 0.0)
    nnz_mfu = 6.0 * nnz * rank * epochs_per_sec / (
        V5E_BF16_PEAK * sess.num_workers)
    return best, rmse_last, layout, mxu_busy, nnz_mfu


def cpu_sgd_mf_samples_per_sec(nu, ni, epochs):
    """numpy minibatch-SGD anchor for the same workload shape."""
    from harp_tpu.io import datagen

    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    rng = np.random.default_rng(0)
    k = 32
    w = (rng.standard_normal((nu, k)) / np.sqrt(k)).astype(np.float32)
    h = (rng.standard_normal((ni, k)) / np.sqrt(k)).astype(np.float32)
    bs = min(8192, len(vals))
    nb = -(-len(vals) // bs)            # include the tail minibatch
    processed = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for b in range(nb):
            sl = slice(b * bs, min((b + 1) * bs, len(vals)))
            r, c, v = rows[sl], cols[sl], vals[sl]
            wr, hc = w[r], h[c]
            err = (v - np.einsum("ij,ij->i", wr, hc))[:, None]
            np.add.at(w, r, 0.05 * (err * hc - 0.01 * wr))
            np.add.at(h, c, 0.05 * (err * wr - 0.01 * hc))
            processed += len(v)
    return processed / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# ALS (BASELINE configs[2] names daal_als alongside SGD-MF — implicit, CSR)
# --------------------------------------------------------------------------- #

def tpu_als_iters_per_sec(nu, ni, iters):
    from harp_tpu.io import datagen
    from harp_tpu.models import als
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.005,
                                              seed=9)
    vals = np.abs(vals)          # implicit mode consumes interaction COUNTS
    cfg = als.ALSConfig(rank=32, lam=0.1, alpha=40.0, iterations=iters,
                        implicit=True)
    model = als.ALS(sess, cfg)
    state = model.prepare(rows, cols, vals, nu, ni, seed=0)
    model.train_prepared(state)                  # compile + warm-up
    best, rmse_last = 0.0, 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        _, _, rmse = model.train_prepared(state)
        dt = time.perf_counter() - t0
        best = max(best, iters / dt)
        rmse_last = float(rmse[-1])
    return best, rmse_last, model.last_layout_stats.get("layout", "sparse")


def cpu_als_iters_per_sec(nu, ni, iters):
    """Implicit (Hu-Koren) ALS anchor: batched normal equations over padded
    neighbor lists — the same formulation the device program uses, on BLAS."""
    from harp_tpu.io import datagen

    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.005,
                                              seed=9)
    vals = np.abs(vals)          # same implicit counts as the device side
    k, lam, alpha = 32, 0.1, 40.0

    def pad(r, c, v, n):
        order = np.argsort(r, kind="stable")
        r, c, v = r[order], c[order], v[order]
        cnt = np.bincount(r, minlength=n)
        m = max(int(cnt.max()), 1)
        idx = np.zeros((n, m), np.int64)
        val = np.zeros((n, m), np.float32)
        msk = np.zeros((n, m), np.float32)
        pos = np.arange(len(r)) - np.concatenate([[0], np.cumsum(cnt)])[r]
        idx[r, pos] = c
        val[r, pos] = v
        msk[r, pos] = 1.0
        return idx, val, msk

    u_lay = pad(rows, cols, vals, nu)
    i_lay = pad(cols, rows, vals, ni)
    rng = np.random.default_rng(0)
    u = (rng.random((nu, k)) / np.sqrt(k)).astype(np.float32)
    v = (rng.random((ni, k)) / np.sqrt(k)).astype(np.float32)
    eye = lam * np.eye(k, dtype=np.float32)

    def half(other, lay):
        idx, val, msk = lay
        x = other[idx] * msk[..., None]          # (n, M, K) masked neighbors
        wts = alpha * val * msk                  # C - 1
        a = (other.T @ other + eye
             + np.matmul(x.transpose(0, 2, 1) * wts[:, None, :], x))
        b = ((msk + wts)[..., None] * x).sum(1)  # Σ C·v over observed
        return np.linalg.solve(a, b[..., None])[..., 0]

    t0 = time.perf_counter()
    for _ in range(iters):
        u = half(v, u_lay)
        v = half(u, i_lay)
    return iters / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# PCA / covariance (BASELINE configs[1] — dense allreduce)
# --------------------------------------------------------------------------- #

def tpu_pca_fits_per_sec(n, d, repeats):
    from harp_tpu.io import datagen
    from harp_tpu.models import stats
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    x_dev = sess.scatter(datagen.dense_points(n, d, seed=2))
    model = stats.PCA(sess)
    # all `repeats` fits run inside ONE compiled program (lax.scan) so the
    # measurement is device work, not the ~0.1-0.4 s per-call dispatch that
    # dominated the round-2 number (VERDICT r2 weak #1)
    model.fit_repeated(x_dev, repeats)           # compile + warmup
    t0 = time.perf_counter()
    w, _, _ = model.fit_repeated(x_dev, repeats)  # returns host arrays
    return repeats / (time.perf_counter() - t0), float(w[0])


def cpu_pca_fits_per_sec(n, d, repeats):
    from harp_tpu.io import datagen

    x = datagen.dense_points(n, d, seed=2).astype(np.float64)
    t0 = time.perf_counter()
    for _ in range(repeats):
        xc = x - x.mean(0)
        cov = (xc.T @ xc) / (n - 1)
        np.linalg.eigh(cov)
    return repeats / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# CGS-LDA (BASELINE configs[3] — rotation + blocked sampling)
# --------------------------------------------------------------------------- #

def tpu_lda_tokens_per_sec(num_docs, vocab, doc_len, topics, epochs):
    from harp_tpu.io import datagen
    from harp_tpu.models import lda
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    num_docs -= num_docs % sess.num_workers
    docs = datagen.lda_corpus(num_docs, vocab, max(2, topics // 2), doc_len,
                              seed=3)
    cfg = lda.LDAConfig(num_topics=topics, vocab=vocab, epochs=epochs)
    model = lda.LDA(sess, cfg)
    state = model.prepare(docs, seed=1)          # host layout + H2D once
    model.fit_prepared(state)                    # compile + warmup
    t0 = time.perf_counter()
    _, _, ll = model.fit_prepared(state)
    dt = time.perf_counter() - t0
    tokens_per_sec = docs.size * epochs / dt
    # analytic flop estimate per token: the blocked-CGS sampling builds the
    # K-topic categorical (≈5 flops/topic: two multiplies, subtract-current,
    # divide, max-guard), normalizes + cumsum-samples (≈3), plus count
    # updates (≈2) → ~8K+2. MFU here documents that CGS is GATHER/SAMPLE
    # bound, not MXU work — the number is honest, and honestly tiny.
    flops_per_token = 8.0 * topics + 2
    mfu = (tokens_per_sec * flops_per_token
           / (V5E_BF16_PEAK * sess.num_workers))
    return tokens_per_sec, float(ll[-1]), mfu


def cpu_lda_tokens_per_sec(num_docs, vocab, doc_len, topics, epochs):
    """Vectorized numpy blocked-CGS sweep — same blocked math as the device."""
    from harp_tpu.io import datagen

    docs = datagen.lda_corpus(num_docs, vocab, max(2, topics // 2), doc_len,
                              seed=3)
    rng = np.random.default_rng(1)
    d, l = docs.shape
    z = rng.integers(0, topics, (d, l))
    ndk = np.zeros((d, topics))
    np.add.at(ndk, (np.arange(d)[:, None], z), 1)
    nwk = np.zeros((vocab, topics))
    np.add.at(nwk, (docs, z), 1)
    nk = ndk.sum(0)
    alpha, beta = 0.1, 0.01
    t0 = time.perf_counter()
    for _ in range(epochs):
        cur = np.zeros((d, l, topics))
        np.put_along_axis(cur, z[..., None], 1.0, axis=2)
        p = ((ndk[:, None, :] - cur + alpha)
             * (nwk[docs] - cur + beta)
             / (nk[None, None, :] - cur + vocab * beta))
        p = np.maximum(p, 1e-12)
        p /= p.sum(-1, keepdims=True)
        u = rng.random((d, l, 1))
        z = (p.cumsum(-1) < u).sum(-1).clip(0, topics - 1)
        ndk = np.zeros((d, topics))
        np.add.at(ndk, (np.arange(d)[:, None], z), 1)
        nwk = np.zeros((vocab, topics))
        np.add.at(nwk, (docs, z), 1)
        nk = ndk.sum(0)
    return docs.size * epochs / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# Mini-batch NN (BASELINE configs[4] — mini-batch allreduce)
# --------------------------------------------------------------------------- #

def tpu_nn_samples_per_sec(n, d, epochs):
    from harp_tpu.io import datagen
    from harp_tpu.models import nn
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    cfg = nn.NNConfig(layers=(256, 128), num_classes=16, lr=0.05,
                      batch_size=512, epochs=epochs)
    import jax.numpy as jnp

    x, y = datagen.classification_data(n, d, cfg.num_classes, seed=4)
    # place once: fit's internal scatter is a no-op on placed arrays, so the
    # timed run measures training, not host->device transfer
    x_dev = sess.scatter(jnp.asarray(x, jnp.float32))
    y_dev = sess.scatter(jnp.asarray(y, jnp.int32))
    model = nn.MLPClassifier(sess, cfg)
    model.fit(x_dev, y_dev, seed=0)              # compile + warmup
    t0 = time.perf_counter()
    losses = model.fit(x_dev, y_dev, seed=0)
    dt = time.perf_counter() - t0
    sps = n * epochs / dt
    # exact MLP flops/sample: fwd 2·Σ(a·b) + bwd 4·Σ(a·b) (dW and dX GEMMs)
    dims = [d] + list(cfg.layers) + [cfg.num_classes]
    param_mults = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    mfu = sps * 6.0 * param_mults / (V5E_BF16_PEAK * sess.num_workers)
    return sps, float(losses[-1]), mfu


def cpu_nn_samples_per_sec(n, d, epochs):
    from harp_tpu.io import datagen

    x, y = datagen.classification_data(n, d, 16, seed=4)
    rng = np.random.default_rng(0)
    dims = [d, 256, 128, 16]
    ws = [rng.standard_normal((a, b)).astype(np.float32) * np.sqrt(2.0 / a)
          for a, b in zip(dims[:-1], dims[1:])]
    bs_ = [np.zeros(b, np.float32) for b in dims[1:]]
    bsz, lr = 512, 0.05
    t0 = time.perf_counter()
    for _ in range(epochs):
        for i in range(0, n - bsz + 1, bsz):
            xb, yb = x[i:i + bsz], y[i:i + bsz]
            acts = [xb]
            h = xb
            for w, b in zip(ws[:-1], bs_[:-1]):
                h = np.maximum(h @ w + b, 0.0)
                acts.append(h)
            logits = h @ ws[-1] + bs_[-1]
            e = np.exp(logits - logits.max(1, keepdims=True))
            probs = e / e.sum(1, keepdims=True)
            probs[np.arange(bsz), yb] -= 1.0
            g = probs / bsz
            for li in range(len(ws) - 1, -1, -1):
                gw = acts[li].T @ g
                gb = g.sum(0)
                if li:
                    g = (g @ ws[li].T) * (acts[li] > 0)
                ws[li] -= lr * gw
                bs_[li] -= lr * gb
    return n * epochs / (time.perf_counter() - t0)


def tpu_sparse_kmeans_iters_per_sec(n, k, d, density, iters):
    """daal_kmeans/allreducecsr at realistic sparsity (VERDICT r4 item 4)."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sparse as sp
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    rows, cols, vals = datagen.sparse_points(n, d, density, seed=11)
    dense0 = np.zeros((k, d), np.float32)
    head = rows < k
    dense0[rows[head], cols[head]] = vals[head]
    model = sp.SparseKMeans(sess, sp.SparseKMeansConfig(k, d, iters))
    state = model.prepare(rows, cols, vals, n)
    model.fit_prepared(state, dense0)            # compile + warmup
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        _, costs = model.fit_prepared(state, dense0)
        best = max(best, iters / (time.perf_counter() - t0))
    return best, len(vals)


def tpu_attention_tokens_per_sec(l=16384, h=8, dh=64, reps=100):
    """Long-context blocked attention (pallas flash at L >= 8192) at the
    per-chip length SP exists for (the r3 full-softmax path needed 8 GB of
    temps here — PERF.md). Causal, one chip; the multi-chip ring adds the
    ppermute hops on top. 100 in-program reps keep the ~0.1 s tunnel
    dispatch near ~5% of the timed call at flash speed (~19 ms/pass)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel import ring_attention as ra

    q = jax.random.normal(jax.random.key(0), (l, h, dh), jnp.float32)

    def run(q0):
        def body(c, _):
            o = ra.blocked_attention(c, c, c, causal=True)
            return c + 1e-20 * o, ()        # carry dependence: no hoisting

        out, _ = jax.lax.scan(body, q0, None, length=reps)
        return out

    fn = jax.jit(run)
    np.asarray(fn(q))                        # compile + warm (D2H forces)
    t0 = time.perf_counter()
    jax.block_until_ready(fn(q))
    dt = time.perf_counter() - t0
    return l * reps / dt


def p2p_event_rtt_us(rounds=200):
    """Host event-plane round trip (send → wait_event → reply → wait): the
    latency the true P2P transport (authenticated, loopback here) delivers.
    BenchmarkMapper's bcast row timed the reference's control-plane links;
    this times ours."""
    import statistics
    import threading

    from harp_tpu.parallel.events import EventQueue
    from harp_tpu.parallel.p2p import P2PTransport

    q0, q1 = EventQueue(), EventQueue()
    t0_ = P2PTransport(q0, rank=0, peers={}, secret=b"bench")
    t1_ = P2PTransport(q1, rank=1, peers={0: t0_.address}, secret=b"bench")
    t0_._peers[1] = t1_.address

    def echo():
        for _ in range(rounds):
            ev = q1.wait(timeout=5.0)
            if ev is None:
                return                  # a lost frame ends the echo cleanly
            t1_.send(0, ev.payload)

    th = threading.Thread(target=echo, daemon=True)
    th.start()
    lat = []
    payload = b"x" * 256
    try:
        for _ in range(rounds):
            t = time.perf_counter()
            t0_.send(1, payload)
            if q0.wait(timeout=5.0) is None:
                break                   # echo died — stop, don't poison
            lat.append((time.perf_counter() - t) * 1e6)   # full round trip
    finally:
        th.join(timeout=10.0)
        t0_.close()
        t1_.close()
    if len(lat) < rounds // 2:
        raise RuntimeError(f"p2p rtt bench lost frames: only {len(lat)}/"
                           f"{rounds} round trips completed")
    return round(statistics.median(lat), 1)


# --------------------------------------------------------------------------- #
# Scaling + collectives (subprocess on the 8-device virtual CPU mesh)
# --------------------------------------------------------------------------- #

def mesh_scaling_and_collectives(timeout=1800):
    # 1800 s: the 1→64 sweep compiles 7 mesh widths and time-shares up to 64
    # virtual devices on what may be a single host core
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                         " --xla_force_host_platform_device_count=64").strip()}
    try:
        out = subprocess.run(
            [sys.executable, "-m", "harp_tpu.benchmark.scaling"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
        if out.returncode == 0:
            return json.loads(out.stdout.strip().splitlines()[-1])
        return {"error": out.stderr[-500:]}
    except Exception as e:             # noqa: BLE001 — bench must not die here
        return {"error": str(e)}


def main():
    small = "--small" in sys.argv
    n, k, d = (100_000, 100, 100) if small else (1_000_000, 100, 100)
    tpu_iters = 50 if small else 200  # long enough to amortize dispatch latency
    cpu_iters = 2 if small else 3

    tpu_ips, final_cost, km_hbm_pct = tpu_kmeans_iters_per_sec(n, k, d,
                                                              tpu_iters)
    # bf16 point storage halves the E-step's dominant bytes; accumulations
    # stay f32 (kmeans.py compute_dtype contract) — the cost row shows the
    # convergence is unchanged
    bf16_ips, bf16_cost, _ = tpu_kmeans_iters_per_sec(
        n, k, d, tpu_iters, compute_dtype="bfloat16")
    cpu_ips = cpu_kmeans_iters_per_sec(n, k, d, cpu_iters)
    skm_n, skm_d = (16384, 128) if small else (262144, 256)
    skm_ips, skm_nnz = tpu_sparse_kmeans_iters_per_sec(
        skm_n, k, skm_d, density=0.05, iters=20 if small else 100)

    nu = 4096 if small else 32768
    sgd_epochs = 20 if small else 100  # in-program epochs amortize dispatch
    sgd_sps, sgd_rmse, sgd_layout, sgd_busy, sgd_nnz_mfu = \
        tpu_sgd_mf_samples_per_sec(nu, nu, epochs=sgd_epochs)
    sgd_cpu = cpu_sgd_mf_samples_per_sec(nu, nu, epochs=1)
    # rank-128 config: fills the MXU's 128-lane tiles (VERDICT r2 #2)
    r128_sps, _, _, r128_busy, r128_nnz_mfu = tpu_sgd_mf_samples_per_sec(
        nu, nu, epochs=sgd_epochs, rank=128)

    an = 2048 if small else 8192
    als_ips, als_rmse, als_layout = tpu_als_iters_per_sec(
        an, an, iters=3 if small else 10)
    als_cpu = cpu_als_iters_per_sec(an, an, iters=1)

    pn, pd = (32768, 64) if small else (262144, 256)
    # enough in-program fits to amortize the fixed dispatch cost
    pca_fps, pca_top = tpu_pca_fits_per_sec(pn, pd,
                                            repeats=50 if small else 100)
    pca_cpu = cpu_pca_fits_per_sec(pn, pd, repeats=2)

    ld, lv, ll_, lk = (256, 300, 32, 8) if small else (2048, 2000, 128, 32)
    # enough epochs inside the single compiled call to amortize the fixed
    # per-dispatch + transfer cost (~0.4s on the tunnel) — same rationale as
    # the 200-iteration K-means config
    lda_tps, lda_ll, lda_mfu = tpu_lda_tokens_per_sec(
        ld, lv, ll_, lk, epochs=20 if small else 100)
    lda_cpu = cpu_lda_tokens_per_sec(ld // 4, lv, ll_, lk, epochs=1)
    # a clueweb-regime corpus (8x the tokens, 4x the vocab, 2x the topics):
    # per-token fixed costs amortize, so this is the throughput a real LDA
    # workload sees (the small config above is BASELINE's toy shape)
    if small:
        lda_big_tps, lda_big_ll = None, None     # skipped — never alias the
        #                                          toy numbers as "large"
    else:
        lda_big_tps, lda_big_ll, _ = tpu_lda_tokens_per_sec(
            8192, 8000, 256, 64, epochs=30)

    nn_n, nn_d = (8192, 64) if small else (65536, 128)
    nn_sps, nn_loss, nn_mfu = tpu_nn_samples_per_sec(
        nn_n, nn_d, epochs=3 if small else 50)
    nn_cpu = cpu_nn_samples_per_sec(nn_n, nn_d, epochs=1)

    attn_l = 2048 if small else 16384
    attn_tps = tpu_attention_tokens_per_sec(l=attn_l)

    mesh = mesh_scaling_and_collectives()
    try:
        rtt_us = p2p_event_rtt_us()
    except Exception as e:             # noqa: BLE001 — bench must not die here
        rtt_us = {"error": str(e)[:200]}

    print(json.dumps({
        "metric": f"kmeans_regroupallgather_iters_per_sec_n{n}_k{k}_d{d}",
        "value": round(tpu_ips, 3),
        "unit": "iters/s",
        "vs_baseline": round(tpu_ips / cpu_ips, 2),
        "baseline_cpu_iters_per_sec": round(cpu_ips, 3),
        "final_cost": final_cost,
        "kmeans_hbm_roofline_pct": round(km_hbm_pct, 1),
        "kmeans_bf16_iters_per_sec": round(bf16_ips, 3),
        "kmeans_bf16_final_cost": bf16_cost,
        "kmeans_vs_xeon36_lb": xeon_lb(tpu_ips / cpu_ips),
        "kmeans_csr_iters_per_sec": round(skm_ips, 2),
        "kmeans_csr_config": f"n={skm_n} d={skm_d} density=0.05 "
                             f"nnz={skm_nnz}",
        "sgd_mf_samples_per_sec": round(sgd_sps),
        "sgd_mf_vs_cpu": round(sgd_sps / sgd_cpu, 2),
        "sgd_mf_vs_xeon36_lb": xeon_lb(sgd_sps / sgd_cpu),
        "sgd_mf_final_rmse": round(sgd_rmse, 4),
        "sgd_mf_layout": sgd_layout,
        "sgd_mf_mxu_busy_pct": round(100 * sgd_busy, 2),
        "sgd_mf_nnz_effective_mfu_pct": round(100 * sgd_nnz_mfu, 3),
        "sgd_mf_rank128_samples_per_sec": round(r128_sps),
        "sgd_mf_rank128_mxu_busy_pct": round(100 * r128_busy, 2),
        "sgd_mf_rank128_nnz_effective_mfu_pct": round(100 * r128_nnz_mfu, 3),
        "als_iters_per_sec": round(als_ips, 3),
        "als_vs_cpu": round(als_ips / als_cpu, 2),
        "als_vs_xeon36_lb": xeon_lb(als_ips / als_cpu),
        "als_final_rmse": round(als_rmse, 4),
        "als_layout": als_layout,
        "pca_fits_per_sec": round(pca_fps, 3),
        "pca_vs_cpu": round(pca_fps / pca_cpu, 2),
        "pca_vs_xeon36_lb": xeon_lb(pca_fps / pca_cpu),
        "pca_top_eigenvalue": round(pca_top, 5),
        "lda_tokens_per_sec": round(lda_tps),
        "lda_vs_cpu": round(lda_tps / lda_cpu, 2),
        "lda_vs_xeon36_lb": xeon_lb(lda_tps / lda_cpu),
        "lda_mfu_pct": round(100 * lda_mfu, 4),
        "lda_final_ll": lda_ll,
        "lda_large_tokens_per_sec": (None if lda_big_tps is None
                                     else round(lda_big_tps)),
        "lda_large_final_ll": lda_big_ll,
        "nn_samples_per_sec": round(nn_sps),
        "nn_vs_cpu": round(nn_sps / nn_cpu, 2),
        "nn_vs_xeon36_lb": xeon_lb(nn_sps / nn_cpu),
        "nn_mfu_pct": round(100 * nn_mfu, 2),
        "nn_final_loss": round(nn_loss, 4),
        "xeon_anchor_note": (
            f"vs_cpu = measured vs ONE modern Zen core (this host has 1 "
            f"core); vs_xeon36_lb = vs_cpu/{XEON_CORES}, a conservative "
            f"lower bound on the ratio vs BASELINE.md's 2x18-core Haswell "
            f"(assumes perfect 36x anchor scaling AND Haswell==Zen "
            f"per-core; both favor the Xeon)"),
        "attention_tokens_per_sec": round(attn_tps),
        "attention_config": f"blocked causal L={attn_l} H=8 Dh=64 (1 chip)",
        "p2p_event_rtt_us": rtt_us,
        "scaling_efficiency": mesh.get("scaling_efficiency", mesh),
        "collectives_8w_cpu_mesh": mesh.get("collectives", {}),
    }))


if __name__ == "__main__":
    main()
