#!/usr/bin/env python
"""Benchmark harness — prints ONE compact JSON line for the driver and writes
the FULL result to BENCH_local.json (VERDICT r4 weak #6: the driver's record
was tail-truncated; the compact line carries the headline keys, the file
carries everything).

Covers the five BASELINE workload configs (BASELINE.json): K-means
regroupallgather (the flagship/primary metric), SGD-MF (rotate pipeline),
PCA/covariance (dense allreduce), CGS-LDA (rotation + blocked sampling), and
mini-batch NN — each anchored against an optimized CPU implementation
(numpy/BLAS — the same linear-algebra core DAAL uses) of the IDENTICAL
workload on this host: the reference publishes no absolute throughput
(BASELINE.md), and the north-star is "match DAAL-on-Xeon iteration
throughput". A subprocess on an 8-device virtual CPU mesh adds the 1→2→4→8
strong-scaling curve and the collective micro-benchmarks
(harp_tpu/benchmark/{scaling,collectives}.py).

Timing method (round 5, VERDICT r4 weak #1/#2/#4 root cause): every device
rate is measured TWO-POINT — the same workload is compiled at a low and a
high in-program iteration count and the rate comes from the iteration-count
delta, so the constant per-dispatch cost of the axon tunnel (~0.3-0.4 s of
dispatch + D2H per call, measured; recorded per row as *_fixed_dispatch_s)
cancels instead of being amortized into the rate. Round ≤4 rates divided by
total wall time and were therefore dominated by that constant for any row
whose device time was < ~1 s — the r4 LDA row recorded 40.5M tokens/s for a
program whose device rate is ~93M (profiler-verified, PERF.md r5). Each
two-point sample is a median of N≥3 alternating runs and ships a spread
column; deltas inside the spread are noise by the data, not by prose.

Usage: python bench.py [--small] [--only group1,group2,...] [--list-groups]

``--only`` re-measures a subset of row groups (names in ROW_GROUPS) without
the full ~all-rows run and MERGES the result into BENCH_local.json instead
of rewriting it; the gc-at-group-boundary behavior is identical to the full
run (a gc precedes every selected group), so a filtered re-measure sees the
same freshly-collected device state.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

V5E_BF16_PEAK = 197e12   # TPU v5e peak bf16 FLOP/s (MFU denominator)
V5E_HBM_GBPS = 819e9     # TPU v5e HBM bandwidth roofline (bytes/s)

# The DAAL-on-Xeon north star (BASELINE.md): the comparison machine is a
# 2x18-core Haswell E5-2699 v3. This host has exactly ONE (modern Zen) core,
# so a measured multicore anchor is impossible; instead every vs-CPU ratio
# also ships a CONSERVATIVE LOWER BOUND on the vs-Xeon ratio: divide by 36,
# i.e. assume the same BLAS anchor scales PERFECTLY linearly to all 36
# Haswell cores AND that a 2015 Haswell core matches this Zen core per-core.
# Both assumptions favor the Xeon (memory-bound kernels scale sublinearly;
# Haswell is slower per-core), so vs_xeon36_lb >= 1 genuinely supports
# "matches DAAL-on-Xeon throughput".
XEON_CORES = 36


def xeon_lb(vs_cpu: float) -> float:
    return round(vs_cpu / XEON_CORES, 2)


# shared two-point protocol: rate from the iteration-count delta so the
# constant tunnel dispatch+fetch tax cancels (harp_tpu/benchmark/timing.py)
from harp_tpu.benchmark.timing import two_point  # noqa: E402


# --------------------------------------------------------------------------- #
# K-means (BASELINE configs[0] — flagship, primary metric)
# --------------------------------------------------------------------------- #

def tpu_kmeans(n, k, d, iters, compute_dtype="float32", lane_pad=True):
    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()  # all visible devices (1 real chip under the driver)
    pts = datagen.dense_points(n - n % sess.num_workers or n, d, seed=7,
                               num_clusters=k)
    n_eff = pts.shape[0] - pts.shape[0] % sess.num_workers
    pts = pts[:n_eff]
    cen0 = datagen.initial_centroids(pts, k, seed=3)
    state = {}

    def build(ni):
        model = km.KMeans(sess, km.KMeansConfig(k, d, ni, "regroupallgather",
                                                compute_dtype=compute_dtype,
                                                lane_pad=lane_pad))
        pts_dev, cen_dev = model.prepare(pts, cen0)
        _, costs = model.fit_prepared(pts_dev, cen_dev)   # compile + warmup
        state[ni] = float(np.asarray(costs)[-1])  # fetch forces execution
        #   (block_until_ready is async on remote-tunnel platforms)

        def timer():
            _, costs = model.fit_prepared(pts_dev, cen_dev)
            np.asarray(costs)
        return timer

    tp = two_point(build, max(iters // 4, 2), iters, 1.0)
    # two utilization views. The r5 two-point rate exposed that the old
    # "2 reads per iteration" HBM model was wrong: XLA fuses distance GEMM +
    # argmin + stats GEMM into ONE pass over the point tiles (the old model
    # read >100% of roofline). hbm: one point-block read per iteration;
    # mxu: the 2·2·N·K·D FLOPs of the two GEMMs — at the flagship shape the
    # iteration is MXU-bound (bf16 point storage ties f32, same FLOPs).
    # mxu counts USEFUL flops (real K and D) — with lane_pad the hardware
    # runs 128-wide tiles either way; the padded row's gain shows up as rate.
    # hbm counts STORED bytes: lane_pad feature-pads the resident block to a
    # 128 multiple, and the E-step streams the padded width.
    bytes_per_point = 2 if compute_dtype == "bfloat16" else 4
    d_stored = -(-d // 128) * 128 if lane_pad else d
    bytes_per_iter = 1.0 * n_eff * d_stored * bytes_per_point
    tp["hbm_one_pass_pct"] = round(100.0 * bytes_per_iter * tp["rate"] / (
        V5E_HBM_GBPS * sess.num_workers), 1)
    tp["mxu_tflops"] = round(4.0 * n_eff * k * d * tp["rate"] / 1e12
                             / sess.num_workers, 1)
    tp["final_cost"] = state[iters]
    tp["lane_pad"] = lane_pad
    return tp


def cpu_kmeans_iters_per_sec(n, k, d, iters):
    """BLAS-backed Lloyd iteration — the DAAL-equivalent CPU anchor."""
    rng = np.random.default_rng(7)
    pts = rng.random((n, d), dtype=np.float32)
    cen = pts[:k].copy()

    def one_iter(cen):
        x2 = (pts * pts).sum(1, keepdims=True)
        c2 = (cen * cen).sum(1)[None, :]
        dist = x2 - 2.0 * pts @ cen.T + c2
        a = dist.argmin(1)
        oh = np.zeros((n, k), np.float32)
        oh[np.arange(n), a] = 1.0
        sums = oh.T @ pts
        cnt = oh.sum(0)[:, None]
        return sums / np.maximum(cnt, 1.0)

    cen = one_iter(cen)     # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        cen = one_iter(cen)
    return iters / (time.perf_counter() - t0)


def tpu_sparse_kmeans(n, k, d, density, iters):
    """daal_kmeans/allreducecsr at realistic sparsity."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sparse as sp
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    rows, cols, vals = datagen.sparse_points(n, d, density, seed=11)
    dense0 = np.zeros((k, d), np.float32)
    head = rows < k
    dense0[rows[head], cols[head]] = vals[head]

    def build(ni):
        model = sp.SparseKMeans(sess, sp.SparseKMeansConfig(k, d, ni))
        state = model.prepare(rows, cols, vals, n)
        _, costs = model.fit_prepared(state, dense0)      # compile + warmup
        np.asarray(costs)

        def timer():
            _, costs = model.fit_prepared(state, dense0)
            np.asarray(costs)
        return timer

    tp = two_point(build, max(iters // 4, 2), iters, 1.0)
    tp["nnz"] = len(vals)
    return tp


# --------------------------------------------------------------------------- #
# SGD-MF (BASELINE configs[2] — rotate pipeline; dense masked-stripe layout)
# --------------------------------------------------------------------------- #

def tpu_sgd_mf(nu, ni, epochs, rank=32):
    """Steady-state training throughput (samples = ratings processed)."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sgd_mf
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    meta = {}

    def build(ne):
        cfg = sgd_mf.SGDMFConfig(rank=rank, lam=0.01, lr=0.05, epochs=ne,
                                 minibatches_per_hop=8)
        model = sgd_mf.SGDMF(sess, cfg)
        state = model.prepare(rows, cols, vals, nu, ni)
        meta["nnz"] = len(vals) - model.last_layout_stats.get(
            "duplicates_dropped", 0)
        meta["layout"] = model.last_layout_stats["layout"]
        _, _, rmse = model.train_prepared(state)          # compile + warm-up
        meta[ne] = float(np.asarray(rmse)[-1])

        def timer():
            _, _, rmse = model.train_prepared(state)
            np.asarray(rmse)
        return timer

    tp = two_point(build, max(epochs // 4, 2), epochs, 1.0)
    nnz = meta["nnz"]
    tp["rate"] *= nnz                        # epochs/s → ratings/s
    tp["final_rmse"] = round(meta[epochs], 4)
    tp["layout"] = meta["layout"]
    # two utilization views: mxu_busy = the dense slab GEMMs the program
    # actually issues (dense layout computes on NaN holes by design);
    # nnz_mfu = only the 6*nnz*rank flops a sparse-exact algorithm needs.
    eps = tp["rate"] / nnz
    tp["mxu_busy_pct"] = round(100 * 6.0 * nu * ni * rank * eps
                               / (V5E_BF16_PEAK * sess.num_workers), 2) \
        if meta["layout"] == "dense" else 0.0
    tp["nnz_effective_mfu_pct"] = round(100 * 6.0 * nnz * rank * eps / (
        V5E_BF16_PEAK * sess.num_workers), 3)
    return tp


def cpu_sgd_mf_samples_per_sec(nu, ni, epochs):
    """numpy minibatch-SGD anchor for the same workload shape."""
    from harp_tpu.io import datagen

    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    rng = np.random.default_rng(0)
    k = 32
    w = (rng.standard_normal((nu, k)) / np.sqrt(k)).astype(np.float32)
    h = (rng.standard_normal((ni, k)) / np.sqrt(k)).astype(np.float32)
    bs = min(8192, len(vals))
    nb = -(-len(vals) // bs)            # include the tail minibatch
    processed = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for b in range(nb):
            sl = slice(b * bs, min((b + 1) * bs, len(vals)))
            r, c, v = rows[sl], cols[sl], vals[sl]
            wr, hc = w[r], h[c]
            err = (v - np.einsum("ij,ij->i", wr, hc))[:, None]
            np.add.at(w, r, 0.05 * (err * hc - 0.01 * wr))
            np.add.at(h, c, 0.05 * (err * wr - 0.01 * hc))
            processed += len(v)
    return processed / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# ALS (BASELINE configs[2] names daal_als alongside SGD-MF — implicit, CSR)
# --------------------------------------------------------------------------- #

def tpu_als(nu, ni, iters, ablate_solve=False):
    from harp_tpu.io import datagen
    from harp_tpu.models import als
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.005,
                                              seed=9)
    vals = np.abs(vals)          # implicit mode consumes interaction COUNTS
    meta = {}

    def build(ni_):
        cfg = als.ALSConfig(rank=32, lam=0.1, alpha=40.0, iterations=ni_,
                            implicit=True, ablate_solve=ablate_solve)
        model = als.ALS(sess, cfg)
        state = model.prepare(rows, cols, vals, nu, ni, seed=0)
        _, _, rmse = model.train_prepared(state)          # compile + warm-up
        meta[ni_] = float(np.asarray(rmse)[-1])
        meta["layout"] = model.last_layout_stats.get("layout", "sparse")

        def timer():
            _, _, rmse = model.train_prepared(state)
            np.asarray(rmse)
        return timer

    tp = two_point(build, max(iters // 3, 2), iters, 1.0)
    tp["final_rmse"] = round(meta[iters], 4)
    tp["layout"] = meta["layout"]
    return tp


def tpu_als_stage(nu, ni, iters, full_row=None):
    """ALS per-iteration stage budget by solve ablation (ISSUE 9 satellite:
    the thinnest north-star margin, lb 5.22, gets a MEASURED stage row —
    the r3/r4 PERF one-off ablation as a reproducible bench sub-row).
    ``ablate_solve=True`` rides identity through the batched k×k SPD solve
    (results wrong, timing only), so full − ablated prices the solve and
    the remainder is gram/gather/allgather + bookkeeping."""
    full = full_row if full_row is not None else tpu_als(nu, ni, iters)
    ablated = tpu_als(nu, ni, iters, ablate_solve=True)
    f_ms, a_ms = full["per_iter_ms"], ablated["per_iter_ms"]
    return {
        "config": f"nu={nu} ni={ni} rank=32 implicit two-point",
        "full_ms_per_iter": f_ms,
        "solve_ablated_ms_per_iter": a_ms,
        "solve_ms_per_iter": round(f_ms - a_ms, 3),
        "solve_share_pct": round(100.0 * max(f_ms - a_ms, 0.0)
                                 / max(f_ms, 1e-9), 1),
        "note": ("solve-ablated results are wrong by construction "
                 "(ALSConfig.ablate_solve); this row prices stages only"),
    }


def cpu_als_iters_per_sec(nu, ni, iters):
    """Implicit (Hu-Koren) ALS anchor: batched normal equations over padded
    neighbor lists — the same formulation the device program uses, on BLAS."""
    from harp_tpu.io import datagen

    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.005,
                                              seed=9)
    vals = np.abs(vals)          # same implicit counts as the device side
    k, lam, alpha = 32, 0.1, 40.0

    def pad(r, c, v, n):
        order = np.argsort(r, kind="stable")
        r, c, v = r[order], c[order], v[order]
        cnt = np.bincount(r, minlength=n)
        m = max(int(cnt.max()), 1)
        idx = np.zeros((n, m), np.int64)
        val = np.zeros((n, m), np.float32)
        msk = np.zeros((n, m), np.float32)
        pos = np.arange(len(r)) - np.concatenate([[0], np.cumsum(cnt)])[r]
        idx[r, pos] = c
        val[r, pos] = v
        msk[r, pos] = 1.0
        return idx, val, msk

    u_lay = pad(rows, cols, vals, nu)
    i_lay = pad(cols, rows, vals, ni)
    rng = np.random.default_rng(0)
    u = (rng.random((nu, k)) / np.sqrt(k)).astype(np.float32)
    v = (rng.random((ni, k)) / np.sqrt(k)).astype(np.float32)
    eye = lam * np.eye(k, dtype=np.float32)

    def half(other, lay):
        idx, val, msk = lay
        x = other[idx] * msk[..., None]          # (n, M, K) masked neighbors
        wts = alpha * val * msk                  # C - 1
        a = (other.T @ other + eye
             + np.matmul(x.transpose(0, 2, 1) * wts[:, None, :], x))
        b = ((msk + wts)[..., None] * x).sum(1)  # Σ C·v over observed
        return np.linalg.solve(a, b[..., None])[..., 0]

    t0 = time.perf_counter()
    for _ in range(iters):
        u = half(v, u_lay)
        v = half(u, i_lay)
    return iters / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# PCA / covariance (BASELINE configs[1] — dense allreduce)
# --------------------------------------------------------------------------- #

def tpu_pca(n, d, repeats):
    from harp_tpu.io import datagen
    from harp_tpu.models import stats
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    x_dev = sess.scatter(datagen.dense_points(n, d, seed=2))
    model = stats.PCA(sess)
    meta = {}

    def build(nr):
        w, _, _ = model.fit_repeated(x_dev, nr)           # compile + warmup
        meta[nr] = float(w[0])

        def timer():
            model.fit_repeated(x_dev, nr)     # returns host arrays (forces)
        return timer

    tp = two_point(build, max(repeats // 4, 2), repeats, 1.0)
    tp["top_eigenvalue"] = round(meta[repeats], 5)
    return tp


def cpu_pca_fits_per_sec(n, d, repeats):
    from harp_tpu.io import datagen

    x = datagen.dense_points(n, d, seed=2).astype(np.float64)
    t0 = time.perf_counter()
    for _ in range(repeats):
        xc = x - x.mean(0)
        cov = (xc.T @ xc) / (n - 1)
        np.linalg.eigh(cov)
    return repeats / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# CGS-LDA (BASELINE configs[3] — rotation + blocked sampling)
# --------------------------------------------------------------------------- #

def tpu_lda(num_docs, vocab, doc_len, topics, epochs, vocab_sub_block=0):
    from harp_tpu.io import datagen
    from harp_tpu.models import lda
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    num_docs -= num_docs % sess.num_workers
    docs = datagen.lda_corpus(num_docs, vocab, max(2, topics // 2), doc_len,
                              seed=3)
    meta = {}

    def build(ne):
        cfg = lda.LDAConfig(num_topics=topics, vocab=vocab, epochs=ne,
                            vocab_sub_block=vocab_sub_block)
        model = lda.LDA(sess, cfg)
        state = model.prepare(docs, seed=1)      # host layout + H2D once
        _, _, ll = model.fit_prepared(state)     # compile + warmup
        meta[ne] = float(ll[-1])
        # per-(doc, sub-block) padding is the sub-block layout's cost —
        # report it NEXT to the throughput it buys
        meta["overhead"] = model.last_layout_stats["overhead"]

        def timer():
            model.fit_prepared(state)            # fetches ll etc. (forces)
        return timer

    tp = two_point(build, max(epochs // 4, 2), epochs, float(docs.size))
    tp["final_ll"] = meta[epochs]
    if vocab_sub_block:
        tp["vocab_sub_block"] = vocab_sub_block
        tp["token_padding_overhead"] = round(meta["overhead"], 3)
    # analytic flop estimate per token: the blocked-CGS sampling builds the
    # K-topic categorical (≈5 flops/topic), normalizes + cumsum-samples (≈3),
    # plus count updates (≈2) → ~8K+2. MFU documents that CGS is
    # GATHER/SAMPLE bound, not MXU work — honest, and honestly tiny.
    tp["mfu_pct"] = round(100 * tp["rate"] * (8.0 * topics + 2)
                          / (V5E_BF16_PEAK * sess.num_workers), 4)
    return tp


def cpu_lda_tokens_per_sec(num_docs, vocab, doc_len, topics, epochs):
    """Vectorized numpy blocked-CGS sweep — same blocked math as the device."""
    from harp_tpu.io import datagen

    docs = datagen.lda_corpus(num_docs, vocab, max(2, topics // 2), doc_len,
                              seed=3)
    rng = np.random.default_rng(1)
    d, l = docs.shape
    z = rng.integers(0, topics, (d, l))
    ndk = np.zeros((d, topics))
    np.add.at(ndk, (np.arange(d)[:, None], z), 1)
    nwk = np.zeros((vocab, topics))
    np.add.at(nwk, (docs, z), 1)
    nk = ndk.sum(0)
    alpha, beta = 0.1, 0.01
    t0 = time.perf_counter()
    for _ in range(epochs):
        cur = np.zeros((d, l, topics))
        np.put_along_axis(cur, z[..., None], 1.0, axis=2)
        p = ((ndk[:, None, :] - cur + alpha)
             * (nwk[docs] - cur + beta)
             / (nk[None, None, :] - cur + vocab * beta))
        p = np.maximum(p, 1e-12)
        p /= p.sum(-1, keepdims=True)
        u = rng.random((d, l, 1))
        z = (p.cumsum(-1) < u).sum(-1).clip(0, topics - 1)
        ndk = np.zeros((d, topics))
        np.add.at(ndk, (np.arange(d)[:, None], z), 1)
        nwk = np.zeros((vocab, topics))
        np.add.at(nwk, (docs, z), 1)
        nk = ndk.sum(0)
    return docs.size * epochs / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# Mini-batch NN (BASELINE configs[4] — mini-batch allreduce)
# --------------------------------------------------------------------------- #

def tpu_nn(n, d, epochs, layers=(256, 128), batch_size=512):
    from harp_tpu.io import datagen
    from harp_tpu.models import nn
    from harp_tpu.session import HarpSession
    import jax.numpy as jnp

    sess = HarpSession()
    n -= n % sess.num_workers
    x, y = datagen.classification_data(n, d, 16, seed=4)
    # place once: fit's internal scatter is a no-op on placed arrays, so the
    # timed run measures training, not host->device transfer
    x_dev = sess.scatter(jnp.asarray(x, jnp.float32))
    y_dev = sess.scatter(jnp.asarray(y, jnp.int32))
    meta = {}

    def build(ne):
        cfg = nn.NNConfig(layers=layers, num_classes=16, lr=0.05,
                          batch_size=batch_size, epochs=ne)
        model = nn.MLPClassifier(sess, cfg)
        losses = model.fit(x_dev, y_dev, seed=0)          # compile + warmup
        meta[ne] = float(losses[-1])

        def timer():
            model.fit(x_dev, y_dev, seed=0)   # returns host losses (forces)
        return timer

    tp = two_point(build, max(epochs // 4, 2), epochs, float(n))
    tp["final_loss"] = round(meta[epochs], 4)
    # exact MLP flops/sample: fwd 2·Σ(a·b) + bwd 4·Σ(a·b) (dW and dX GEMMs)
    dims = [d] + list(layers) + [16]
    param_mults = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    tp["mfu_pct"] = round(100 * tp["rate"] * 6.0 * param_mults
                          / (V5E_BF16_PEAK * sess.num_workers), 2)
    tp["config"] = (f"n={n} d={d} layers={'x'.join(map(str, layers))} "
                    f"batch={batch_size}")
    return tp


def cpu_nn_samples_per_sec(n, d, epochs, layers=(256, 128), batch_size=512):
    from harp_tpu.io import datagen

    x, y = datagen.classification_data(n, d, 16, seed=4)
    rng = np.random.default_rng(0)
    dims = [d] + list(layers) + [16]
    ws = [rng.standard_normal((a, b)).astype(np.float32) * np.sqrt(2.0 / a)
          for a, b in zip(dims[:-1], dims[1:])]
    bs_ = [np.zeros(b, np.float32) for b in dims[1:]]
    bsz, lr = batch_size, 0.05
    t0 = time.perf_counter()
    for _ in range(epochs):
        for i in range(0, n - bsz + 1, bsz):
            xb, yb = x[i:i + bsz], y[i:i + bsz]
            acts = [xb]
            h = xb
            for w, b in zip(ws[:-1], bs_[:-1]):
                h = np.maximum(h @ w + b, 0.0)
                acts.append(h)
            logits = h @ ws[-1] + bs_[-1]
            e = np.exp(logits - logits.max(1, keepdims=True))
            probs = e / e.sum(1, keepdims=True)
            probs[np.arange(bsz), yb] -= 1.0
            g = probs / bsz
            for li in range(len(ws) - 1, -1, -1):
                gw = acts[li].T @ g
                gb = g.sum(0)
                if li:
                    g = (g @ ws[li].T) * (acts[li] > 0)
                ws[li] -= lr * gw
                bs_[li] -= lr * gb
    return n * epochs / (time.perf_counter() - t0)


def tpu_attention(l=16384, h=8, dh=64, reps=100, head_pack=None,
                  causal=True):
    """Long-context blocked attention (pallas flash at L >= 8192) at the
    per-chip length SP exists for. Causal, one chip; the multi-chip ring adds
    the ppermute hops on top.

    ``head_pack``: None = the dispatcher's auto gate (packed at Dh<=64);
    False pins the unpacked layout via HARP_FLASH_HEADPACK=0 so the r7
    block-sparse-grid leg can be priced separately from the lane-packing
    leg (the env var is restored after the measurement)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel import ring_attention as ra

    q = jax.random.normal(jax.random.key(0), (l, h, dh), jnp.float32)

    def build(nr):
        def run(q0):
            def body(c, _):
                o = ra.blocked_attention(c, c, c, causal=causal)
                return c + 1e-20 * o, ()    # carry dependence: no hoisting

            out, _ = jax.lax.scan(body, q0, None, length=nr)
            return out

        fn = jax.jit(run)
        np.asarray(fn(q))                    # compile + warm (D2H forces)

        def timer():
            # block_until_ready is async over the tunnel: force with a tiny
            # D2H fetch (any element of the scan carry needs every rep)
            np.asarray(fn(q)[0, 0])
        return timer

    prev = os.environ.get("HARP_FLASH_HEADPACK")
    try:
        if head_pack is False:
            os.environ["HARP_FLASH_HEADPACK"] = "0"
        tp = two_point(build, max(reps // 4, 2), reps, float(l))
    finally:
        if head_pack is False:
            if prev is None:
                os.environ.pop("HARP_FLASH_HEADPACK", None)
            else:
                os.environ["HARP_FLASH_HEADPACK"] = prev
    tp["config"] = (f"causal={causal} L={l} H={h} Dh={dh} "
                    f"head_pack={'auto' if head_pack is None else head_pack}")
    return tp


def tpu_kernel_svm(n, d, iterations):
    """Kernel-SVM dual training rate (VERDICT r4 weak #5: the r4 components
    shipped correctness-tested but unbenchmarked). One projected-gradient
    iteration = one ring-rotated Gram matvec: N²/W kernel evaluations per
    worker per iteration, never materializing the N×N Gram."""
    from harp_tpu.models import svm
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rng = np.random.default_rng(21)
    n -= n % sess.num_workers
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (x @ rng.standard_normal(d) + 0.3 * rng.standard_normal(n)
         > 0).astype(np.int32)
    y_signed = (2.0 * y - 1.0).astype(np.float32)
    cap = np.full((n,), 10.0, np.float32)

    def build(ni):
        model = svm.KernelSVM(sess, svm.KernelSVMConfig(
            kernel="rbf", sigma=2.0, c=10.0, iterations=ni))
        model._fit_padded(x, y_signed, cap)      # compile + warm

        def timer():
            model._fit_padded(x, y_signed, cap)
        return timer

    tp = two_point(build, max(iterations // 4, 2), iterations, 1.0)
    tp["kernel_evals_per_sec"] = round(tp["rate"] * n * n)
    tp["config"] = f"rbf n={n} d={d}"
    # convergence-budget view: the early stop ends the same program when
    # relative dual progress dies (one extra compile, small run)
    es = svm.KernelSVM(sess, svm.KernelSVMConfig(
        kernel="rbf", sigma=2.0, c=10.0, iterations=iterations,
        early_stop_tol=1e-5))
    es._fit_padded(x, y_signed, cap)
    tp["early_stop_iters_at_1e-5"] = int(es.n_iter_)
    # the RECORDED firing config (VERDICT r5 leftover: the row above shows
    # the stop never fired at the bench shape — this one provably does;
    # the firing iteration is dual-ascent math, device-independent)
    xf, yf = svm.early_stop_recorded_problem()
    esf = svm.KernelSVM(sess, svm.KernelSVMConfig(
        **svm.EARLY_STOP_RECORDED_CONFIG))
    esf.fit(xf, yf)
    tp["early_stop_recorded"] = {
        "config": "rbf sigma=2 c=1 n=128 d=3 seed=12 tol=1e-5 budget=2000 "
                  "(svm.EARLY_STOP_RECORDED_CONFIG)",
        "fired_at_iteration": int(esf.n_iter_),
        "budget": svm.EARLY_STOP_RECORDED_CONFIG["iterations"],
    }
    return tp


def tpu_mds(n, iterations):
    """WDA-MDS stress-majorization rate (SMACOF + weighted-V CG solve)."""
    from harp_tpu.models import mds
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rng = np.random.default_rng(13)
    n -= n % sess.num_workers
    pts = rng.standard_normal((n, 3)).astype(np.float32)
    dist = np.sqrt(((pts[:, None] - pts[None, :]) ** 2).sum(-1))
    wts = 0.5 + rng.random((n, n)).astype(np.float32)   # non-uniform weights
    wts = (wts + wts.T) / 2
    meta = {}

    def build(ni):
        model = mds.WDAMDS(sess, mds.MDSConfig(dim=3, iterations=ni,
                                               cg_iters=8))
        state = model.prepare(dist, wts, seed=0)   # H2D of the N² matrices
        #   happens ONCE here, not in the timed region (it is ~8 s/call on
        #   the tunnel and swamped the iteration delta in the first r5 run)
        _, stress = model.fit_prepared(state)            # compile + warm
        meta[ni] = float(stress[-1])

        def timer():
            model.fit_prepared(state)
        return timer

    tp = two_point(build, max(iterations // 4, 2), iterations, 1.0)
    tp["final_stress"] = meta[iterations]
    tp["config"] = f"n={n} dim=3 cg_iters=8"
    return tp


def tpu_distributed_sort(n, repeats):
    """Distributed sort rate (odd-even block transposition; on one chip this
    measures the XLA sort core the multi-worker path is built from)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.ops import linalg
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    x = np.random.default_rng(17).standard_normal(n).astype(np.float32)

    def build(nr):
        def looped(a):
            def body(c, _):
                out = linalg.distributed_sort(jnp.roll(c, 7))
                return out, ()
            out, _ = jax.lax.scan(body, a, None, length=nr)
            return out

        prog = sess.spmd(looped, in_specs=(sess.shard(),),
                         out_specs=sess.shard())
        dev = sess.scatter(x)
        np.asarray(prog(dev))                    # compile + warm (D2H forces)

        def timer():
            np.asarray(prog(dev)[:1])            # force, tiny fetch
        return timer

    tp = two_point(build, max(repeats // 4, 2), repeats, float(n))
    tp["config"] = f"n={n} f32"
    return tp


def tpu_csr_cov(n, d, density, repeats):
    """CSR covariance/PCA statistics rate (densify-GEMM gram path)."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sparse as sp
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    rows, cols, vals = datagen.sparse_points(n, d, density, seed=23)
    cov = sp.CSRCovariance(sess)

    def build(nr):
        cov.compute_repeated(rows, cols, vals, n, d, nr)  # compile + warm

        def timer():
            cov.compute_repeated(rows, cols, vals, n, d, nr)
        return timer

    tp = two_point(build, max(repeats // 4, 2), repeats, 1.0)
    tp["nnz"] = len(vals)
    tp["config"] = f"n={n} d={d} density={density}"
    return tp


def kmeans_from_files(n=131072, d=64, k=64, iters=20, parts=8):
    """File-driven flagship workflow (VERDICT r4 missing #1): the
    reference's entire pipeline was files-in (README.md:148-160 — generated
    HDFS part-files consumed by KMeansLauncher). Times the host load stage
    (native C++ parser on local part-files vs the numpy fallback through
    the fsspec memory:// store) and the full load→split→scatter→fit wall.
    Host work has no tunnel tax, so these are plain medians-of-3."""
    import shutil
    import statistics
    import tempfile

    import jax.numpy as jnp

    from harp_tpu.io import datagen, loaders
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n -= n % sess.num_workers
    pts = datagen.dense_points(n, d, seed=31, num_clusters=k)
    tmp = tempfile.mkdtemp(prefix="harp_bench_km_")
    try:
        for i, block in enumerate(np.array_split(pts, parts)):
            np.savetxt(os.path.join(tmp, f"part-{i:05d}"), block,
                       fmt="%.6f", delimiter=",")
        paths = loaders.list_files(tmp)
        bytes_total = sum(os.path.getsize(p) for p in paths)

        def timed3(fn, reduce):
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn()
                ts.append(time.perf_counter() - t0)
            return reduce(ts)

        def med(fn):
            return timed3(fn, statistics.median)

        def best(fn):
            # peak parse rate: min-of-3 — host-side bandwidth benchmarks
            # report best-case; one bench-process GC/VM hiccup should not
            # stand as the parser rate
            return timed3(fn, min)

        t_native = best(lambda: loaders.load_dense_csv(paths))
        # numpy fallback: the same bytes through the fsspec memory:// store
        # (URL paths bypass the native parser by design)
        import fsspec

        mem = fsspec.filesystem("memory")
        mem_paths = []
        for p in paths:
            mp = f"/bench_km/{os.path.basename(p)}"
            with open(p, "rb") as src, mem.open(mp, "wb") as dst:
                dst.write(src.read())
            mem_paths.append("memory://" + mp)
        t_numpy = best(lambda: loaders.load_dense_csv(mem_paths))

        # full workflow: list → threaded load → scatter → 20-iteration fit
        model = km.KMeans(sess, km.KMeansConfig(k, d, iters,
                                                "regroupallgather"))
        cen0 = datagen.initial_centroids(pts, k, seed=32)

        def full():
            loaded = loaders.load_dense_csv(loaders.list_files(tmp))
            pts_dev, cen_dev = model.prepare(loaded, cen0)
            _, costs = model.fit_prepared(pts_dev, cen_dev)
            np.asarray(costs)

        full()                                   # compile + warm
        t_full = med(full)
        try:
            mem.rm("/bench_km", recursive=True)
        except Exception:          # noqa: BLE001 — best-effort cleanup
            pass
        return {
            "config": f"n={n} d={d} k={k} iters={iters} parts={parts}",
            "csv_bytes": bytes_total,
            "load_native_mb_per_sec": round(bytes_total / t_native / 1e6, 1),
            "load_numpy_fallback_mb_per_sec": round(
                bytes_total / t_numpy / 1e6, 1),
            "native_vs_numpy": round(t_numpy / t_native, 2),
            "load_scatter_fit_wall_s": round(t_full, 3),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def tpu_collectives_quantized(small=False):
    """Quantized-collective busbw rows (ISSUE 6): int8/bf16 vs f32 wire
    formats for allreduce + the rotation hop at >= 2 payload sizes, on the
    session mesh (on-chip when the driver runs this; the committed record
    carries null-with-note rows when no TPU is reachable). busbw prices the
    QUANTIZED wire bytes (int8 payload + scales), so a codec's win shows as
    equal-or-better busbw at 1/4 (int8) or 1/2 (bf16) the moved volume —
    see collectives_quantized_note in the record."""
    from harp_tpu.benchmark import collectives as bc
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    sizes = (16, 64) if small else (64, 1024)
    return bc.bench_collectives_quantized(sess, sizes_kb=list(sizes),
                                          loops=20)


def tpu_telemetry_overhead(small=False):
    """Telemetry on/off delta on the kmeans fit loop (ISSUE 7 acceptance:
    < 2% on-chip, asserted here). Runs the fit_checkpointed dispatch shape —
    one compiled iteration per host step with the cost fetched at each
    boundary — with and without `telemetry.record_chunk` + the comm ledger,
    both sides timing and fetching identically, so the delta is exactly the
    telemetry layer. Returns None on a CPU-only host (null-with-note
    convention; the driver's on-chip run fills it)."""
    import statistics
    import tempfile

    import jax

    if all(d.platform == "cpu" for d in jax.devices()):
        return None
    from harp_tpu import telemetry
    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    n, k, d = (100_000, 100, 100) if small else (1_000_000, 100, 100)
    iters = 30 if small else 200
    pts = datagen.dense_points(n, d, seed=7, num_clusters=k)
    pts = pts[: len(pts) - len(pts) % sess.num_workers]
    cen0 = datagen.initial_centroids(pts, k, seed=3)
    model = km.KMeans(sess, km.KMeansConfig(k, d, 1))
    p, c0 = model.prepare(pts, cen0)
    step = model._fit

    def run(ledger=None, record=False):
        cen = c0
        t0 = time.perf_counter()
        for i in range(iters):
            it0 = time.perf_counter()
            cen, cost = step(p, cen)
            loss = [float(np.asarray(cost)[0])]       # the boundary D2H
            wall = time.perf_counter() - it0
            if record:
                telemetry.record_chunk("kmeans", start=i, losses=loss,
                                       wall_s=wall, ledger=ledger)
        return time.perf_counter() - t0

    run()                                             # compile + warm
    t_off = statistics.median(run() for _ in range(3))
    tele_dir = tempfile.mkdtemp(prefix="harp-bench-tele-")
    telemetry.configure(tele_dir, interval=16)
    ledger = telemetry.ledger_for("kmeans", comm="regroupallgather",
                                  scale=model.comm_scale(),
                                  exact=sess.num_workers == 8)
    try:
        t_on = statistics.median(run(ledger, record=True)
                                 for _ in range(3))
    finally:
        telemetry.disable()
    overhead_pct = round(100.0 * (t_on - t_off) / t_off, 3)
    # the acceptance contract rides IN the row (pass flag), and main() exits
    # nonzero on failure AFTER committing the record — the failing number
    # must land in BENCH_local.json, not vanish into a swallowed assert
    return {"config": f"n={len(pts)} k={k} d={d} iters={iters} "
                      f"dispatch=1-iter-chunks",
            "off_iters_per_sec": round(iters / t_off, 1),
            "on_iters_per_sec": round(iters / t_on, 1),
            "overhead_pct": overhead_pct,
            "contract": "overhead_pct < 2.0 (ISSUE 7 acceptance)",
            "pass": bool(overhead_pct < 2.0),
            "telemetry_dir": tele_dir}


def tpu_ring_dma_overlap(small=False):
    """Fused ring-DMA overlap ablation (ISSUE 9 acceptance): hidden comm
    time on two ring workloads — the LDA wt-block rotation
    (benchmark/lda_overlap, fused twins) and ring attention
    (benchmark/ring_overlap). Each row carries unfused / rotation-ablated /
    fused timings plus ``fused_hidden_fraction`` = the share of the
    measured hop cost the in-kernel ``make_async_remote_copy`` transport
    hides. Returns None on a CPU-only host (null-with-note convention; the
    driver's on-chip run fills it — the fused kernels only exist on TPU,
    the CPU fallback is transport-identical to ppermute by design)."""
    import jax

    if all(d.platform == "cpu" for d in jax.devices()):
        return None
    from harp_tpu.benchmark import lda_overlap, ring_overlap
    from harp_tpu.session import HarpSession

    workers = HarpSession().num_workers
    row = {
        "lda_rotation": lda_overlap.measure(epochs=4 if small else 8,
                                            reps=3, fused=True),
        "ring_attention": ring_overlap.measure(
            l_local=2048 if small else 8192, reps=3),
    }
    if workers < 2:
        row["note"] = (
            f"single-device mesh (workers={workers}): ring hops are "
            f"self-loops, so the ablation degenerates — a >=2-chip run is "
            f"needed for a meaningful overlap fraction")
    return row


def tpu_serving(small=False):
    """Online-serving load rows (ISSUE 10 acceptance): p50/p99 latency +
    QPS at >=3 traffic mixes against a 2-worker local serving gang
    (harp_tpu/serve/ router + continuous micro-batcher + resident
    dispatches; benchmark/serving_load.py). The per-mix latency rows are
    published THROUGH telemetry (record_timing -> steps.jsonl, same
    percentile format as the straggler reports); the returned row carries
    the telemetry event count as proof. Unlike the pure-device groups this
    one always measures — the router/batcher stack is host-side — but the
    row's `device` field says what the dispatches ran on, and a CPU-mesh
    row carries the re-measure note for the driver's on-chip run."""
    import tempfile

    from harp_tpu import telemetry
    from harp_tpu.benchmark import serving_load
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    tele_dir = tempfile.mkdtemp(prefix="harp-bench-serve-")
    telemetry.configure(tele_dir, interval=1)
    try:
        row = serving_load.measure(
            sess, requests_per_mix=300 if small else 900, num_clients=3,
            trace_sample=4)
    finally:
        telemetry.disable()
    rank_file = os.path.join(tele_dir, "rank0", "steps.jsonl")
    n_events = n_spans = 0
    if os.path.exists(rank_file):
        with open(rank_file) as f:
            for line in f:
                n_events += '"kind": "timing"' in line
                n_spans += '"kind": "span"' in line
    row["telemetry_timing_events"] = n_events
    # the r13 proof the spans flowed THROUGH telemetry: every sampled
    # request's breakdown is also a kind:"span" JSONL event
    row["telemetry_span_events"] = n_spans
    row["telemetry_dir"] = tele_dir
    return row


def tpu_serving_quant(small=False):
    """Quantized-serving rows (ISSUE 17 acceptance): f32 vs int8 resident
    gangs at the recsys bench shapes (2048 users x 512 items, rank 64,
    k=10) — per-mix QPS/p99 for both modes measured by the same closed-
    loop machinery, per-model resident_bytes + the f32/int8 reduction
    ratio, and the sampled top-k overlap through the full quantized
    request path (int8 dispatch wire + f16-encoded replies). The
    acceptance bars (resident reduction >= 3x on the top-k model, mean
    overlap >= 0.95) are gated AFTER the record commits, like
    telemetry_overhead. resident_bytes and overlap are device-independent;
    a CPU-mesh row carries the latency re-measure note."""
    from harp_tpu.benchmark import serving_quant
    from harp_tpu.session import HarpSession

    return serving_quant.measure(
        HarpSession(), requests_per_mix=200 if small else 600,
        overlap_sample=64 if small else 128, num_clients=3)


def tpu_serving_fleet(small=False):
    """Fleet-operations rows (ISSUE 14 acceptance): the recovery-blip run
    (a SEPARATE-PROCESS serving gang under retrying load absorbs a
    scripted ``kill@request=N`` — spare restored through the on-device
    reshard engine, zero failed requests, the recovery-window p99 blip
    measured against steady state), the live-refresh run (factor epochs
    pushed mid-traffic through the versioned snapshot swap — torn reads
    asserted zero by checking every reply against ITS version's
    reference), and the hot-key run (Zipfian load, router reply cache off
    vs on — hit rate, lookup skew, and the hot subset's tail). See
    harp_tpu/benchmark/serving_fleet.py for the scenario scripts."""
    from harp_tpu.benchmark import serving_fleet
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    return {
        "recovery": serving_fleet.measure_recovery(
            requests_per_client=60 if small else 120),
        # ISSUE 15: the SAME scripted kill with a pre-warmed artifact
        # store — the elastic replacement loads every dispatch instead of
        # compiling (its post-mortem trace_counts ride the row), plus the
        # rolling-restart cold-start comparison (spawn -> first reply,
        # artifacts off vs on, with the worker's published stage split)
        "recovery_aot": serving_fleet.measure_recovery(
            requests_per_client=60 if small else 120,
            prebuild_artifacts=True),
        "refresh": serving_fleet.measure_refresh(
            sess, requests_per_client=100 if small else 200),
        "hotkey": serving_fleet.measure_hotkey(
            sess, requests_per_client=150 if small else 400,
            zipf_alpha=1.2),
        "restart": serving_fleet.measure_restart(
            repeats=2 if small else 3),
        # ISSUE 16: QPS ramp with the demand-driven autoscaler closing the
        # loop — worker count must follow the ramp up AND back down, the
        # scale-up journaled with its placement version, zero trace
        # counts, and AOT-store loads (the elastic worker never compiles).
        # Subprocess on the 8-device virtual mesh (reshard_bench idiom):
        # the restore-built movers and the AOT store's traced layouts only
        # agree at the fleet's real mesh width, not on this process's
        # possibly-single device
        "autoscale": _autoscale_subprocess(small),
    }


def _autoscale_subprocess(small=False):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                         " --xla_force_host_platform_device_count=8"
                         ).strip()}
    out = subprocess.run(
        [sys.executable, "-m", "harp_tpu.benchmark.serving_fleet",
         f"--ramp_hold_s={5.0 if small else 8.0}"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        return {"error": out.stderr[-500:]}
    return json.loads(out.stdout.strip().splitlines()[-1])


def tpu_reshard(small=False):
    """On-device reshard rows (ISSUE 11): seconds + bytes moved for a
    world-size-changing factor-table redistribution vs the PR 8 host
    gather-and-resplit on the same maps (harp_tpu/benchmark/reshard_bench).
    Two legs: ``cpu_mesh`` is MEASURED in a subprocess on the 8-worker
    virtual CPU mesh (the engine is backend-agnostic — same plan, same
    traced program shape as on chip), committed per the CPU-session
    convention; ``gb_scale`` is the multi-chip on-chip row (a >=2-chip
    mesh moving a GB-scale table over ICI) and stays null-with-note until
    the driver's on-chip run."""
    import jax

    rows, rank = (65536, 32) if small else (262144, 64)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                         " --xla_force_host_platform_device_count=8"
                         ).strip()}
    out = subprocess.run(
        [sys.executable, "-m", "harp_tpu.benchmark.reshard_bench",
         f"--rows={rows}", f"--rank={rank}"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1200)
    if out.returncode != 0:
        return {"cpu_mesh": {"error": out.stderr[-500:]}, "gb_scale": None}
    cpu_row = json.loads(out.stdout.strip().splitlines()[-1])
    row = {"cpu_mesh": cpu_row}
    tpu_devs = [d for d in jax.devices() if d.platform != "cpu"]
    if len(tpu_devs) >= 2:
        from harp_tpu.benchmark import reshard_bench

        row["gb_scale"] = reshard_bench.measure(
            num_workers=len(tpu_devs), rows=2_097_152, rank=128,
            old_world=max(len(tpu_devs) // 2, 1))
    else:
        row["gb_scale"] = None
        row["gb_scale_note"] = (
            f"GB-scale on-chip reshard needs a >=2-chip mesh; this session "
            f"sees {len(tpu_devs)} non-CPU device(s) — the driver's "
            f"on-chip run fills it (rows=2097152 rank=128 f32 ~= 1 GB "
            f"table, chunk-bounded ICI rounds)")
    return row


def tpu_ingest(small=False):
    """Streaming-ingestion rows (ISSUE 18 acceptance): GB-scale part-file
    stream through the io/pipeline engine — load MB/s for the bounded-queue
    drain, serialized vs prefetch-overlapped twin walls (overlap_efficiency,
    gated >= 1.3x where overlap is physically available — see the row's
    overlap_gate/overlap_note), end-to-end stream->assemble->Lloyd-fit wall,
    the per-stage telemetry timer table, and the distributed COO->CSR
    regroup on the jaxlint-pinned ingest_coo_regroup all_to_all schedule.
    The host-side stages (read/parse/chunk) measure for real on any host;
    the compute/H2D columns of a CPU-mesh row carry the usual on-chip
    re-measure convention."""
    from harp_tpu.benchmark import ingest as bench_ingest

    if small:
        return bench_ingest.bench_ingest(
            total_mb=48, parts=6, chunk_rows=16384, fit_iters=2)
    return bench_ingest.bench_ingest()


def p2p_event_rtt_us(rounds=200):
    """Host event-plane round trip (send → wait_event → reply → wait): the
    latency the true P2P transport (authenticated, loopback) delivers.
    BenchmarkMapper's bcast row timed the reference's control-plane links;
    this times ours."""
    import statistics as st
    import threading

    from harp_tpu.parallel.events import EventQueue
    from harp_tpu.parallel.p2p import P2PTransport

    q0, q1 = EventQueue(), EventQueue()
    # loopback benchmark: bind 127.0.0.1 explicitly so the authenticated
    # transports never open an externally reachable port (ADVICE r4)
    t0_ = P2PTransport(q0, rank=0, peers={}, secret=b"bench",
                       host="127.0.0.1")
    t1_ = P2PTransport(q1, rank=1, peers={0: t0_.address}, secret=b"bench",
                       host="127.0.0.1")
    t0_._peers[1] = t1_.address

    def echo():
        for _ in range(rounds):
            ev = q1.wait(timeout=5.0)
            if ev is None:
                return                  # a lost frame ends the echo cleanly
            t1_.send(0, ev.payload)

    th = threading.Thread(target=echo, daemon=True)
    th.start()
    lat = []
    payload = b"x" * 256
    try:
        for _ in range(rounds):
            t = time.perf_counter()
            t0_.send(1, payload)
            if q0.wait(timeout=5.0) is None:
                break                   # echo died — stop, don't poison
            lat.append((time.perf_counter() - t) * 1e6)   # full round trip
    finally:
        th.join(timeout=10.0)
        t0_.close()
        t1_.close()
    if len(lat) < rounds // 2:
        raise RuntimeError(f"p2p rtt bench lost frames: only {len(lat)}/"
                           f"{rounds} round trips completed")
    return round(st.median(lat), 1)


# --------------------------------------------------------------------------- #
# Scaling + collectives (subprocess on the 8-device virtual CPU mesh)
# --------------------------------------------------------------------------- #

def mesh_scaling_and_collectives(timeout=1800):
    # 1800 s: the 1→64 sweep compiles 7 mesh widths and time-shares up to 64
    # virtual devices on what may be a single host core
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                         " --xla_force_host_platform_device_count=64").strip()}
    try:
        out = subprocess.run(
            [sys.executable, "-m", "harp_tpu.benchmark.scaling"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
        if out.returncode == 0:
            return json.loads(out.stdout.strip().splitlines()[-1])
        return {"error": out.stderr[-500:]}
    except Exception as e:             # noqa: BLE001 — bench must not die here
        return {"error": str(e)}


# Row GROUPS --only can select (comma-separated). Each group is
# self-contained (its CPU anchor rides along); dependent keys reuse an
# already-measured group's result when both are selected.
ROW_GROUPS = ("kmeans", "kmeans_padded128", "kmeans_csr", "sgd_mf", "als",
              "pca", "lda", "lda_large", "lda_clueweb_subblock", "nn",
              "nn_compute_bound", "attention", "attention_blocksparse",
              "kernel_svm", "mds", "sort", "csr_cov", "kmeans_from_files",
              "p2p", "mesh", "collectives_quantized", "telemetry_overhead",
              "ring_dma_overlap", "serving", "serving_quant", "reshard",
              "ingest")


def main():
    argv = sys.argv[1:]
    if "--list-groups" in argv:
        # the discoverable twin of --only's validator: one group name per
        # line, nothing else — `bench.py --only "$(bench.py --list-groups
        # | ...)"` composes, and tier-1 pins this list to ROW_GROUPS
        for g in ROW_GROUPS:
            print(g)
        sys.exit(0)
    small = "--small" in argv
    only = None
    for i, a in enumerate(argv):
        if a == "--only":
            if i + 1 >= len(argv):
                # a bare --only must NOT silently fall through to the full
                # run (which rewrites the whole committed record)
                sys.stderr.write(
                    f"--only needs a value; valid: {','.join(ROW_GROUPS)}\n")
                sys.exit(2)
            only = argv[i + 1]
        elif a.startswith("--only="):
            only = a.split("=", 1)[1]
    if only is not None:
        selected = tuple(s.strip() for s in only.split(",") if s.strip())
        unknown = [s for s in selected if s not in ROW_GROUPS]
        if unknown or not selected:
            sys.stderr.write(
                f"--only: unknown row group(s) {unknown or only!r}; "
                f"valid: {','.join(ROW_GROUPS)}\n")
            sys.exit(2)
    else:
        selected = ROW_GROUPS
    run = set(selected)

    def want(name):
        return name in run

    detail = {"timing_method": (
        "two-point: rate from the wall-clock delta between a low and a high "
        "in-program iteration count (median of 3 alternating runs each) — "
        "the constant axon-tunnel dispatch+D2H tax per call cancels and is "
        "recorded separately as fixed_dispatch_s; spread_pct = (max-min)/"
        "median of the high-count samples")}
    compact = {}

    # gc between ROW GROUPS: accumulated device-buffer pressure inside the
    # long bench process measurably perturbs later rows (r5 found
    # nn_compute_bound varying by seconds until a gc preceded it). The
    # boundary gc runs before every selected group, so a --only re-measure
    # of a single row sees the same freshly-collected state it would in the
    # full run.
    import gc

    started = []

    def begin(name):
        if started:
            gc.collect()
        started.append(name)

    # iteration counts: HIGH enough that each two-point delta carries
    # >= ~1-2 s of device time — the delta must stand clear of the tunnel's
    # per-call jitter (timing.py low_resolution note); scan-based epoch
    # loops make compile time independent of the count
    n, k, d = (100_000, 100, 100) if small else (1_000_000, 100, 100)
    tpu_iters = 50 if small else 2000
    cpu_iters = 2 if small else 3

    km = None
    if want("kmeans"):
        begin("kmeans")
        km = tpu_kmeans(n, k, d, tpu_iters)
        # bf16 point storage halves the E-step's dominant bytes;
        # accumulations stay f32 (kmeans.py compute_dtype contract)
        km_bf16 = tpu_kmeans(n, k, d, tpu_iters, compute_dtype="bfloat16")
        cpu_ips = cpu_kmeans_iters_per_sec(n, k, d, cpu_iters)
        detail.update({
            "kmeans": km, "kmeans_bf16": km_bf16,
            "kmeans_cpu_anchor_iters_per_sec": round(cpu_ips, 3)})
        compact.update({
            "metric": f"kmeans_regroupallgather_iters_per_sec_n{n}_k{k}_d{d}",
            "value": round(km["rate"], 1),
            "unit": "iters/s",
            "vs_baseline": round(km["rate"] / cpu_ips, 2),
            "kmeans_vs_xeon36_lb": xeon_lb(km["rate"] / cpu_ips),
            "kmeans_spread_pct": km["spread_pct"],
            "kmeans_bf16_iters_per_sec": round(km_bf16["rate"], 1)})

    if want("kmeans_padded128"):
        # the r6 lane-packing row: K and D padded to 128-lane MXU tiles
        # with masked phantom centroids (KMeansConfig.lane_pad — the
        # default, so the padded rate IS the flagship rate; measured fresh
        # if the kmeans group was filtered out) vs the same config with
        # lane_pad=False (the pre-r6 100-wide tiles), same two-point
        # protocol. The delta is pure layout: identical math, masked pads.
        begin("kmeans_padded128")
        km_pad = km if km is not None else tpu_kmeans(n, k, d, tpu_iters)
        km_nopad = tpu_kmeans(n, k, d, tpu_iters, lane_pad=False)
        detail["kmeans_padded128"] = km_pad
        detail["kmeans_lane_pad_off"] = km_nopad
        detail["kmeans_lane_pad_speedup"] = round(
            km_pad["rate"] / max(km_nopad["rate"], 1e-9), 3)
        compact["kmeans_padded128_iters_per_sec"] = round(km_pad["rate"], 1)
        compact["kmeans_lane_pad_speedup"] = (
            detail["kmeans_lane_pad_speedup"])

    if want("kmeans_csr"):
        begin("kmeans_csr")
        skm_n, skm_d = (16384, 128) if small else (262144, 256)
        skm = tpu_sparse_kmeans(skm_n, k, skm_d, density=0.05,
                                iters=20 if small else 400)
        detail["kmeans_csr"] = skm
        compact["kmeans_csr_iters_per_sec"] = round(skm["rate"], 1)

    if want("sgd_mf"):
        begin("sgd_mf")
        nu = 4096 if small else 32768
        sgd_epochs = 20 if small else 400
        sgd = tpu_sgd_mf(nu, nu, epochs=sgd_epochs)
        sgd_cpu = cpu_sgd_mf_samples_per_sec(nu, nu, epochs=1)
        # rank-128 config: fills the MXU's 128-lane tiles
        sgd128 = tpu_sgd_mf(nu, nu, epochs=sgd_epochs, rank=128)
        detail.update({
            "sgd_mf": sgd, "sgd_mf_rank128": sgd128,
            "sgd_mf_cpu_anchor_samples_per_sec": round(sgd_cpu)})
        compact.update({
            "sgd_mf_samples_per_sec": round(sgd["rate"]),
            "sgd_mf_vs_xeon36_lb": xeon_lb(sgd["rate"] / sgd_cpu),
            "sgd_mf_rank128_samples_per_sec": round(sgd128["rate"])})

    if want("als"):
        begin("als")
        an = 2048 if small else 8192
        als = tpu_als(an, an, iters=6 if small else 120)
        als_cpu = cpu_als_iters_per_sec(an, an, iters=1)
        # r10: the measured stage budget (solve share by ablation) rides
        # the als group — the thinnest north-star margin gets a row, not
        # an assertion
        als_stages = tpu_als_stage(an, an, iters=6 if small else 120,
                                   full_row=als)
        detail.update({
            "als": als, "als_cpu_anchor_iters_per_sec": round(als_cpu, 4),
            "als_stage_budget": als_stages})
        compact.update({
            "als_iters_per_sec": round(als["rate"], 2),
            "als_vs_xeon36_lb": xeon_lb(als["rate"] / als_cpu),
            "als_solve_share_pct": als_stages["solve_share_pct"]})

    if want("pca"):
        begin("pca")
        pn, pd = (32768, 64) if small else (262144, 256)
        pca = tpu_pca(pn, pd, repeats=50 if small else 1000)
        pca_cpu = cpu_pca_fits_per_sec(pn, pd, repeats=2)
        detail.update({
            "pca": pca, "pca_cpu_anchor_fits_per_sec": round(pca_cpu, 3)})
        compact.update({
            "pca_fits_per_sec": round(pca["rate"], 1),
            "pca_vs_xeon36_lb": xeon_lb(pca["rate"] / pca_cpu)})

    if want("lda"):
        begin("lda")
        ld, lv, ll_, lk = ((256, 300, 32, 8) if small
                           else (2048, 2000, 128, 32))
        lda = tpu_lda(ld, lv, ll_, lk, epochs=20 if small else 800)
        lda_cpu = cpu_lda_tokens_per_sec(ld // 4, lv, ll_, lk, epochs=1)
        detail.update({
            "lda": lda, "lda_cpu_anchor_tokens_per_sec": round(lda_cpu)})
        compact.update({
            "lda_tokens_per_sec": round(lda["rate"]),
            "lda_vs_xeon36_lb": xeon_lb(lda["rate"] / lda_cpu),
            "lda_spread_pct": lda["spread_pct"]})

    if want("lda_large"):
        begin("lda_large")
        # a clueweb-regime corpus (8x the tokens, 4x the vocab, 2x the
        # topics): per-token fixed costs amortize, so this is the throughput
        # a real LDA workload sees (the small config is BASELINE's toy shape)
        lda_big = None if small else tpu_lda(8192, 8000, 256, 64, epochs=100)
        detail["lda_large"] = lda_big
        compact["lda_large_tokens_per_sec"] = (
            None if lda_big is None else round(lda_big["rate"]))

    if want("lda_clueweb_subblock"):
        begin("lda_clueweb_subblock")
        # the r6 vocab-sub-block row: same clueweb-regime corpus, tokens
        # bucketized per 128-wide vocab sub-block so the scatter GEMM's
        # FLOPs scale with 128 instead of vpb=8064 (the measured r5
        # crossover config) — the row that cashes the 540M tokens/s
        # no-scatter ceiling. token_padding_overhead rides in the detail.
        lda_sub = None if small else tpu_lda(8192, 8000, 256, 64, epochs=100,
                                             vocab_sub_block=128)
        detail["lda_clueweb_subblock"] = lda_sub
        compact["lda_clueweb_subblock_tokens_per_sec"] = (
            None if lda_sub is None else round(lda_sub["rate"]))

    if want("nn"):
        begin("nn")
        nn_n, nn_d = (8192, 64) if small else (65536, 128)
        nn = tpu_nn(nn_n, nn_d, epochs=4 if small else 4000)
        nn_cpu = cpu_nn_samples_per_sec(nn_n, nn_d, epochs=1)
        detail.update({
            "nn": nn, "nn_cpu_anchor_samples_per_sec": round(nn_cpu)})
        compact.update({
            "nn_samples_per_sec": round(nn["rate"]),
            "nn_vs_xeon36_lb": xeon_lb(nn["rate"] / nn_cpu)})

    if want("nn_compute_bound"):
        # compute-bound NN config (VERDICT r4 weak #1): bigger batch +
        # hidden sizes — still mini-batch allreduce SGD
        # (NNDaalCollectiveMapper.java:47), but the per-step GEMMs are large
        # enough that the MXU, not allreduce latency, sets the floor. The
        # begin() gc matters most here (biggest-footprint config; r5 saw
        # multi-second variance from accumulated HBM pressure without it).
        begin("nn_compute_bound")
        if small:
            nn_big, nn_big_cpu = None, None
        else:
            nn_big = tpu_nn(65536, 512, epochs=150, layers=(2048, 1024),
                            batch_size=8192)
            nn_big_cpu = cpu_nn_samples_per_sec(65536, 512, epochs=1,
                                                layers=(2048, 1024),
                                                batch_size=8192)
        detail.update({
            "nn_compute_bound": nn_big,
            "nn_compute_bound_cpu_anchor": (None if nn_big_cpu is None
                                            else round(nn_big_cpu))})
        compact.update({
            "nn_compute_bound_samples_per_sec": (
                None if nn_big is None else round(nn_big["rate"])),
            "nn_compute_bound_vs_xeon36_lb": (
                None if nn_big is None
                else xeon_lb(nn_big["rate"] / nn_big_cpu)),
            "nn_compute_bound_mfu_pct": (
                None if nn_big is None else nn_big["mfu_pct"])})

    attn = None
    if want("attention"):
        begin("attention")
        attn_l = 2048 if small else 16384
        attn = tpu_attention(l=attn_l, reps=100 if small else 200)
        detail.update({
            "attention": attn,
            "attention_config": (
                f"blocked causal L={attn_l} H=8 Dh=64 (1 chip)")})
        compact["attention_tokens_per_sec"] = round(attn["rate"])

    if want("attention_blocksparse"):
        # r7 rows, three legs of the flash rebuild at the r5 bench shape
        # (L=16k causal; VERDICT r5 #1 target >= 2M tokens/s at Dh=64):
        #  * blocksparse — trapezoid grid alone (head packing pinned OFF):
        #    comparable head-to-head with the r5 1.10M row, isolates the
        #    dead-block DMA removal;
        #  * headpacked — trapezoid + two-heads-per-128-lane packing: the
        #    Dh=64 DEFAULT dispatch, i.e. the SAME config the attention
        #    group times — reused when both groups run (one number, not two
        #    drifting copies of it), measured fresh only under --only;
        #  * dh128 — Dh=128 heads (no packing applies: lanes already full),
        #    quantifying what the Dh=64 padding cost either way.
        # --small pins L=2048, BELOW the use_flash_pallas L>=8192 crossover:
        # every leg would time the XLA scan and the legs' deltas would be
        # scheduler noise wearing kernel labels — emit null instead.
        begin("attention_blocksparse")
        if small:
            bs = hp = d128 = None
        else:
            bs = tpu_attention(l=16384, reps=200, head_pack=False)
            hp = attn if attn is not None else tpu_attention(l=16384,
                                                             reps=200)
            d128 = tpu_attention(l=16384, h=4, dh=128, reps=200)
        detail.update({
            "attention_causal_blocksparse": bs,
            "attention_headpacked": hp,
            "attention_dh128": d128})
        compact.update({
            "attention_causal_blocksparse_tokens_per_sec": (
                None if bs is None else round(bs["rate"])),
            "attention_headpacked_tokens_per_sec": (
                None if hp is None else round(hp["rate"])),
            "attention_dh128_tokens_per_sec": (
                None if d128 is None else round(d128["rate"]))})

    if want("kernel_svm"):
        # r4-component rows (VERDICT r4 weak #5: implemented but
        # unbenchmarked)
        begin("kernel_svm")
        svm_n, svm_d, svm_it = ((2048, 16, 200) if small
                                else (16384, 32, 1000))
        ksvm = tpu_kernel_svm(svm_n, svm_d, svm_it)
        detail["kernel_svm"] = ksvm
        compact["kernel_svm_iters_per_sec"] = round(ksvm["rate"], 1)

    if want("mds"):
        begin("mds")
        mds_row = tpu_mds(1024 if small else 4096,
                          iterations=100 if small else 600)
        detail["mds"] = mds_row
        compact["mds_iters_per_sec"] = round(mds_row["rate"], 1)

    if want("sort"):
        begin("sort")
        sort_row = tpu_distributed_sort(1 << 20 if small else 1 << 22,
                                        repeats=20 if small else 200)
        detail["distributed_sort"] = sort_row
        compact["sort_rows_per_sec"] = round(sort_row["rate"])

    if want("csr_cov"):
        begin("csr_cov")
        cc_n, cc_d = (16384, 128) if small else (262144, 256)
        csr_cov = tpu_csr_cov(cc_n, cc_d, density=0.05,
                              repeats=50 if small else 400)
        detail["csr_covariance"] = csr_cov
        compact["csr_cov_per_sec"] = round(csr_cov["rate"], 1)

    if want("kmeans_from_files"):
        begin("kmeans_from_files")
        km_files = kmeans_from_files(n=16384 if small else 131072,
                                     d=64, k=64, iters=20)
        detail["kmeans_from_files"] = km_files
        compact["load_native_mb_per_sec"] = km_files["load_native_mb_per_sec"]

    if want("p2p"):
        begin("p2p")
        try:
            rtt_us = p2p_event_rtt_us()
        except Exception as e:         # noqa: BLE001 — bench must not die here
            rtt_us = {"error": str(e)[:200]}
        detail["p2p_event_rtt_us"] = rtt_us
        compact["p2p_event_rtt_us"] = rtt_us

    if want("mesh"):
        begin("mesh")
        mesh = mesh_scaling_and_collectives()
        detail.update({
            "scaling_efficiency": mesh.get("scaling_efficiency", mesh),
            "collectives_8w_cpu_mesh": mesh.get("collectives", {})})

    if want("collectives_quantized"):
        begin("collectives_quantized")
        try:
            qrows = tpu_collectives_quantized(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            qrows = {"error": str(e)[:200]}
        detail["collectives_quantized"] = qrows
        if isinstance(qrows, list):
            for r in qrows:
                if r["op"] == "allreduce" and r["codec"] in ("int8", "bf16"):
                    compact[f"allreduce_{r['codec']}_busbw_gbps"] = (
                        r["busbw_gbps"])

    if want("telemetry_overhead"):
        begin("telemetry_overhead")
        try:
            trow = tpu_telemetry_overhead(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            trow = {"error": str(e)[:200]}
        detail["telemetry_overhead"] = trow
        if trow is None:
            detail["bench_schema_note_r9"] = (
                "r9 adds the telemetry_overhead group (bench.py --only "
                "telemetry_overhead): kmeans fit loop in 1-iteration "
                "dispatch chunks with and without harp_tpu.telemetry "
                "record_chunk + comm-ledger at every boundary; the row "
                "asserts the on/off delta < 2% (ISSUE 7 acceptance) — "
                "committed null because no TPU was reachable from this "
                "session (CPU-only devices); the driver's on-chip bench "
                "run fills it. The CPU-flavor contract (telemetry per-step "
                "cost < 2% of a measured kmeans step) IS asserted in "
                "tier-1: tests/test_telemetry.py "
                "test_telemetry_overhead_cpu_smoke.")
        elif isinstance(trow, dict) and "overhead_pct" in trow:
            compact["telemetry_overhead_pct"] = trow["overhead_pct"]
            compact["telemetry_overhead_pass"] = trow["pass"]

    if want("ring_dma_overlap"):
        begin("ring_dma_overlap")
        try:
            rrow = tpu_ring_dma_overlap(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            rrow = {"error": str(e)[:200]}
        detail["ring_dma_overlap"] = rrow
        if rrow is None:
            detail["bench_schema_note_r10"] = (
                "r10 adds the ring_dma_overlap group (bench.py --only "
                "ring_dma_overlap): the fused ring-DMA overlap ablation on "
                "two ring workloads — LDA wt-block rotation "
                "(benchmark/lda_overlap fused twins) and ring attention "
                "(benchmark/ring_overlap) — each row carrying unfused / "
                "rotation-ablated / fused timings and "
                "fused_hidden_fraction. Committed null because no TPU was "
                "reachable from this session (CPU-only devices; the fused "
                "make_async_remote_copy kernels only lower on TPU, and "
                "the CPU fallback is transport-identical to ppermute by "
                "design so its delta is dispatch noise). The driver's "
                "on-chip run fills it; fused == unfused bitwise parity "
                "and the row schema ARE asserted in tier-1 "
                "(tests/test_ring_dma.py). The als group also gains "
                "als_stage_budget (solve share by ALSConfig.ablate_solve "
                "ablation) — measured whenever the als group runs; null "
                "for the same no-TPU reason until the driver's run.")
        elif isinstance(rrow, dict) and "ring_attention" in rrow:
            compact["ring_dma_lda_hidden_fraction"] = (
                rrow["lda_rotation"].get("fused_hidden_fraction"))
            compact["ring_dma_attn_hidden_fraction"] = (
                rrow["ring_attention"].get("fused_hidden_fraction"))

    if want("serving"):
        begin("serving")
        try:
            srow = tpu_serving(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            srow = {"error": str(e)[:200]}
        detail["serving"] = srow
        if isinstance(srow, dict) and "mixes" in srow:
            mixed = srow["mixes"].get("mixed", {})
            compact.update({
                "serving_mixed_p50_ms": mixed.get("p50_ms"),
                "serving_mixed_p99_ms": mixed.get("p99_ms"),
                "serving_mixed_qps": mixed.get("qps"),
                "serving_device": srow.get("device")})
            rec = srow.get("reconciliation") or {}
            sb = srow.get("stage_breakdown") or {}
            compact.update({
                "serving_dispatch_p50_ms": sb.get("dispatch",
                                                  {}).get("p50_ms"),
                "serving_span_p50_ratio": rec.get("p50_ratio"),
                "serving_span_mean_ratio": rec.get("mean_ratio")})
        # r15 fleet rows (ISSUE 14): recovery blip (separate-process gang,
        # scripted kill, reshard-engine spare restore), live refresh under
        # load (versioned swap, torn reads asserted zero), hot-key cache
        # vs the unmitigated Zipfian baseline
        begin("serving_fleet")
        try:
            frow = tpu_serving_fleet(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            frow = {"error": str(e)[:200]}
        detail["serving_fleet"] = frow
        if isinstance(frow, dict) and "recovery" in frow:
            rec_row = frow["recovery"]
            rec_aot = frow.get("recovery_aot", {})
            ref_row = frow.get("refresh", {})
            hot_row = frow.get("hotkey", {})
            rst_row = frow.get("restart", {})
            compact.update({
                "fleet_recovery_errors": rec_row.get("errors"),
                "fleet_recovery_s": rec_row.get("observed_recovery_s"),
                "fleet_recovery_p99_blip_ms":
                    (rec_row.get("recovery_window") or {}).get("p99_ms"),
                "fleet_recovery_aot_s": rec_aot.get("observed_recovery_s"),
                "restart_to_first_reply_s":
                    (rst_row.get("no_aot") or {}).get(
                        "restart_to_first_reply_s"),
                "restart_to_first_reply_aot_s":
                    (rst_row.get("aot") or {}).get(
                        "restart_to_first_reply_s"),
                "fleet_refresh_torn_reads": ref_row.get("torn_reads"),
                "fleet_refresh_errors": ref_row.get("errors"),
                "fleet_hotkey_hit_rate":
                    ((hot_row.get("cached") or {}).get("cache")
                     or {}).get("hit_rate"),
                "fleet_hotkey_hot_p99_speedup":
                    hot_row.get("hot_p99_speedup")})
            asc_row = frow.get("autoscale", {})
            asc_up = asc_row.get("scale_up") or {}
            compact.update({
                "fleet_autoscale_errors": asc_row.get("errors"),
                "fleet_autoscale_wrong": asc_row.get("wrong_results"),
                "fleet_autoscale_peak_workers":
                    asc_row.get("peak_workers"),
                "fleet_autoscale_final_workers":
                    asc_row.get("final_workers"),
                "fleet_autoscale_up_trace_count":
                    (sum(asc_up["trace_counts"].values())
                     if asc_up.get("trace_counts") else None)})

    if want("serving_quant"):
        begin("serving_quant")
        try:
            qsrow = tpu_serving_quant(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            qsrow = {"error": str(e)[:200]}
        detail["serving_quant"] = qsrow
        detail["bench_schema_note_r17"] = (
            "r17 adds the serving_quant group (bench.py --only "
            "serving_quant): f32 vs int8 resident serving gangs at the "
            "recsys bench shapes (2048x512, rank 64, k=10) — per-mix "
            "QPS/p99 for both modes, per-model resident_bytes with the "
            "f32/int8 reduction ratio, and the sampled top-k overlap "
            "through the full quantized path (int8 dispatch wire, "
            "f16-encoded replies). resident_bytes and overlap are "
            "device-independent; on a CPU-mesh session the latency "
            "columns price CPU dispatches and the driver's on-chip run "
            "re-measures them (same schema, device='tpu').")
        if isinstance(qsrow, dict) and "resident_reduction" in qsrow:
            i8 = qsrow["modes"]["int8"]["mixes"].get("topk_heavy", {})
            f32 = qsrow["modes"]["f32"]["mixes"].get("topk_heavy", {})
            compact.update({
                "serving_quant_topk_reduction":
                    qsrow["resident_reduction"].get("topk"),
                "serving_quant_overlap_mean":
                    qsrow["topk_overlap"]["mean"],
                "serving_quant_int8_p99_ms": i8.get("p99_ms"),
                "serving_quant_f32_p99_ms": f32.get("p99_ms"),
                "serving_quant_device": qsrow.get("device")})

    if want("reshard"):
        begin("reshard")
        try:
            rsrow = tpu_reshard(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            rsrow = {"error": str(e)[:200]}
        detail["reshard"] = rsrow
        cpu_mesh = rsrow.get("cpu_mesh") if isinstance(rsrow, dict) else None
        if isinstance(cpu_mesh, dict) and "reshard_seconds" in cpu_mesh:
            compact.update({
                "reshard_seconds": cpu_mesh["reshard_seconds"],
                "reshard_bytes_moved": cpu_mesh["reshard_bytes_moved"],
                "reshard_host_vs_device_speedup":
                    cpu_mesh["host_vs_device_speedup"]})

    if want("ingest"):
        begin("ingest")
        try:
            irow = tpu_ingest(small)
        except Exception as e:     # noqa: BLE001 — bench must not die here
            irow = {"error": str(e)[:200]}
        detail["ingest"] = irow
        detail["bench_schema_note_r19"] = (
            "r19 adds the ingest group (bench.py --only ingest): the "
            "streaming ingestion engine (io/pipeline) at the ~1 GB "
            "part-file size — stream_load_mb_per_sec for the full "
            "bounded-queue drain, the serialized (prefetch-off) vs "
            "overlapped twin walls with overlap_efficiency, the "
            "end-to-end stream->assemble->fit wall, the per-stage timer "
            "table (list/count/read/parse/chunk/regroup/h2d/compute), "
            "and the distributed COO->CSR regroup row (device all_to_all "
            "on the jaxlint-pinned ingest_coo_regroup budget schedule). "
            "The overlap >= 1.3x acceptance gate applies where overlap "
            "is physically available (overlap_gate='on': multi-core host "
            "or accelerator compute); on this 1-core CPU host the twins "
            "time-share one core, the measured ratio rides in the row "
            "and the driver's on-chip run re-measures it — same "
            "convention as the telemetry_overhead/ring_dma_overlap "
            "rows.")
        if isinstance(irow, dict) and "stream_load_mb_per_sec" in irow:
            compact.update({
                "ingest_load_mb_per_sec": irow["stream_load_mb_per_sec"],
                "ingest_overlap_efficiency": irow["overlap_efficiency"],
                "ingest_e2e_wall_s": irow["e2e_stream_fit_wall_s"]})

    detail["xeon_anchor_note"] = (
        f"vs_cpu = measured vs ONE modern Zen core (this host has 1 "
        f"core); vs_xeon36_lb = vs_cpu/{XEON_CORES}, a conservative "
        f"lower bound on the ratio vs BASELINE.md's 2x18-core Haswell "
        f"(assumes perfect 36x anchor scaling AND Haswell==Zen "
        f"per-core; both favor the Xeon)")

    # a filtered run MERGES into the existing record (re-measuring one row
    # must not wipe the others); a full run rewrites it
    path = os.path.join(REPO, "BENCH_local.json")
    full = {}
    if only is not None and os.path.exists(path):
        try:
            with open(path) as f:
                full = json.load(f)
        except Exception:              # noqa: BLE001 — corrupt file: rewrite
            full = {}
    full.update(detail)
    with open(path, "w") as f:
        json.dump(full, f, indent=1)

    # compact driver line: headline + one rate per workload; full numbers,
    # configs, spreads and notes live in BENCH_local.json
    compact.update({
        "timing": "two-point (fixed tunnel dispatch tax cancelled); "
                  "full detail in BENCH_local.json",
        "detail_file": "BENCH_local.json",
    })
    if only is not None:
        compact["only"] = ",".join(selected)
    print(json.dumps(compact))

    # acceptance-gated rows fail the bench AFTER the record is committed —
    # the number is on disk either way, and CI sees the breach
    trow = detail.get("telemetry_overhead")
    if isinstance(trow, dict) and trow.get("pass") is False:
        sys.stderr.write(
            f"bench: telemetry_overhead contract FAILED "
            f"({trow['overhead_pct']}% >= 2%)\n")
        sys.exit(1)
    qsrow = detail.get("serving_quant")
    if isinstance(qsrow, dict) and "resident_reduction" in qsrow:
        red = qsrow["resident_reduction"].get("topk") or 0.0
        ovl = qsrow["topk_overlap"]["mean"]
        if red < 3.0 or ovl < 0.95:
            sys.stderr.write(
                f"bench: serving_quant contract FAILED (topk resident "
                f"reduction {red}x < 3x or overlap {ovl} < 0.95)\n")
            sys.exit(1)
    irow = detail.get("ingest")
    if (isinstance(irow, dict) and irow.get("overlap_gate") == "on"
            and irow.get("overlap_pass") is False):
        sys.stderr.write(
            f"bench: ingest overlap contract FAILED (efficiency "
            f"{irow['overlap_efficiency']}x < 1.3x with overlap gate on)\n")
        sys.exit(1)


if __name__ == "__main__":
    main()
