#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Flagship workload (BASELINE.json configs[0] scaled to TPU): K-means
regroupallgather. The reference publishes no absolute throughput (BASELINE.md), so
``vs_baseline`` anchors against an optimized CPU implementation (numpy/BLAS — the
same linear-algebra core DAAL uses) of the IDENTICAL workload on this host: the
north-star is "match DAAL-on-Xeon iteration throughput" and this measures exactly
that ratio on available hardware.

Usage: python bench.py [--small]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def tpu_kmeans_iters_per_sec(n, k, d, iters):
    import jax.numpy as jnp
    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession

    sess = HarpSession()  # all visible devices (1 real chip under the driver)
    pts = datagen.dense_points(n - n % sess.num_workers or n, d, seed=7,
                               num_clusters=k)
    n_eff = pts.shape[0] - pts.shape[0] % sess.num_workers
    pts = pts[:n_eff]

    model = km.KMeans(sess, km.KMeansConfig(k, d, iters, "regroupallgather"))
    pts_dev, cen_dev = model.prepare(pts, datagen.initial_centroids(pts, k, seed=3))
    _, costs = model.fit_prepared(pts_dev, cen_dev)   # compile + warmup
    np.asarray(costs)  # fetch forces execution (block_until_ready is async on
    #                    remote-tunnel platforms)
    best, final_cost = 0.0, 0.0
    for trial in range(3):
        cen_t = sess.replicate_put(
            jnp.asarray(datagen.initial_centroids(pts, k, seed=100 + trial)))
        t0 = time.perf_counter()
        _, costs = model.fit_prepared(pts_dev, cen_t)
        final_cost = float(np.asarray(costs)[-1])
        best = max(best, iters / (time.perf_counter() - t0))
    return best, final_cost


def cpu_kmeans_iters_per_sec(n, k, d, iters):
    """BLAS-backed Lloyd iteration — the DAAL-equivalent CPU anchor."""
    rng = np.random.default_rng(7)
    pts = rng.random((n, d), dtype=np.float32)
    cen = pts[:k].copy()
    # one warmup iter
    def one_iter(cen):
        x2 = (pts * pts).sum(1, keepdims=True)
        c2 = (cen * cen).sum(1)[None, :]
        dist = x2 - 2.0 * pts @ cen.T + c2
        a = dist.argmin(1)
        oh = np.zeros((n, k), np.float32)
        oh[np.arange(n), a] = 1.0
        sums = oh.T @ pts
        cnt = oh.sum(0)[:, None]
        return sums / np.maximum(cnt, 1.0)

    cen = one_iter(cen)
    t0 = time.perf_counter()
    for _ in range(iters):
        cen = one_iter(cen)
    return iters / (time.perf_counter() - t0)


def tpu_sgd_mf_samples_per_sec(nu, ni, epochs):
    """Secondary north-star (BASELINE: 'SGD-MF samples/sec'): steady-state
    training throughput of the rotation-pipeline MF, device + host prep."""
    from harp_tpu.io import datagen
    from harp_tpu.models import sgd_mf
    from harp_tpu.session import HarpSession

    sess = HarpSession()
    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    cfg = sgd_mf.SGDMFConfig(rank=32, lam=0.01, lr=0.05, epochs=epochs,
                             minibatches_per_hop=8)
    model = sgd_mf.SGDMF(sess, cfg)
    state = model.prepare(rows, cols, vals, nu, ni)
    model.fit_prepared(state)                    # compile + warm-up
    best, rmse_last = 0.0, 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        _, _, rmse = model.fit_prepared(state)
        dt = time.perf_counter() - t0
        best = max(best, len(vals) * epochs / dt)
        rmse_last = float(rmse[-1])
    return best, rmse_last


def cpu_sgd_mf_samples_per_sec(nu, ni, epochs):
    """numpy minibatch-SGD anchor for the same workload shape."""
    from harp_tpu.io import datagen

    rows, cols, vals = datagen.sparse_ratings(nu, ni, rank=16, density=0.01,
                                              seed=5)
    rng = np.random.default_rng(0)
    k = 32
    w = (rng.standard_normal((nu, k)) / np.sqrt(k)).astype(np.float32)
    h = (rng.standard_normal((ni, k)) / np.sqrt(k)).astype(np.float32)
    bs = min(8192, len(vals))
    nb = -(-len(vals) // bs)            # include the tail minibatch
    processed = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for b in range(nb):
            sl = slice(b * bs, min((b + 1) * bs, len(vals)))
            r, c, v = rows[sl], cols[sl], vals[sl]
            wr, hc = w[r], h[c]
            err = (v - np.einsum("ij,ij->i", wr, hc))[:, None]
            np.add.at(w, r, 0.05 * (err * hc - 0.01 * wr))
            np.add.at(h, c, 0.05 * (err * wr - 0.01 * hc))
            processed += len(v)
    return processed / (time.perf_counter() - t0)


def main():
    small = "--small" in sys.argv
    n, k, d = (100_000, 100, 100) if small else (1_000_000, 100, 100)
    tpu_iters = 50 if small else 200  # long enough to amortize dispatch latency
    cpu_iters = 2 if small else 3

    tpu_ips, final_cost = tpu_kmeans_iters_per_sec(n, k, d, tpu_iters)
    cpu_ips = cpu_kmeans_iters_per_sec(n, k, d, cpu_iters)

    nu = 2048 if small else 8192
    sgd_sps, sgd_rmse = tpu_sgd_mf_samples_per_sec(nu, nu, epochs=3)
    sgd_cpu = cpu_sgd_mf_samples_per_sec(nu, nu, epochs=1)

    print(json.dumps({
        "metric": f"kmeans_regroupallgather_iters_per_sec_n{n}_k{k}_d{d}",
        "value": round(tpu_ips, 3),
        "unit": "iters/s",
        "vs_baseline": round(tpu_ips / cpu_ips, 2),
        "baseline_cpu_iters_per_sec": round(cpu_ips, 3),
        "final_cost": final_cost,
        "sgd_mf_samples_per_sec": round(sgd_sps),
        "sgd_mf_vs_cpu": round(sgd_sps / sgd_cpu, 2),
        "sgd_mf_final_rmse": round(sgd_rmse, 4),
    }))


if __name__ == "__main__":
    main()
