#!/usr/bin/env python
"""Regenerate the canonical tiny datasets (reference parity:
``/root/reference/datasets`` ships per-algorithm sample inputs consumed by
every daal_* launcher — VERDICT r4 missing #2).

Every fixture is deterministic (fixed seeds), small enough to commit, split
into part-files (the HDFS directory-of-part-files idiom the loaders and the
CLI's file flags consume), and matches the format its subcommand expects::

    python datasets/generate.py          # rewrites datasets/* in place

Consumed by: ``harp_tpu.run {kmeans,pca} --points-file``,
``svm --train-file``, ``{sgd_mf,als} --ratings-file``,
``lda --corpus-file``, ``subgraph --template-file``, and the
kmeans_from_files bench row.
"""

from __future__ import annotations

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

from harp_tpu.io import datagen  # noqa: E402


def _write_parts(dirname, blocks, fmt, note, delimiter=None):
    path = os.path.join(HERE, dirname)
    os.makedirs(path, exist_ok=True)
    for old in os.listdir(path):
        if old.startswith("part-"):
            os.remove(os.path.join(path, old))
    for i, block in enumerate(blocks):
        kw = {} if delimiter is None else {"delimiter": delimiter}
        np.savetxt(os.path.join(path, f"part-{i:05d}"), block, fmt=fmt, **kw)
    with open(os.path.join(path, "_README"), "w") as f:
        f.write(note + "\n")


def main() -> None:
    # kmeans: 512 x 16 dense points around 8 centers, 4 part-files
    pts = datagen.dense_points(512, 16, seed=40, num_clusters=8)
    _write_parts("kmeans", np.split(pts, 4), "%.6f",
                 "dense CSV points (512 x 16, 8 clusters); harp_tpu.run "
                 "kmeans --points-file datasets/kmeans", delimiter=",")

    # pca: 512 x 12 dense points
    x = datagen.dense_points(512, 12, seed=41)
    _write_parts("pca", np.split(x, 4), "%.6f",
                 "dense CSV points (512 x 12); harp_tpu.run pca "
                 "--points-file datasets/pca", delimiter=",")

    # sgd_mf + als: COO ratings "row col value", 2 part-files each
    for name, seed in (("sgd_mf", 42), ("als", 43)):
        rows, cols, vals = datagen.sparse_ratings(256, 256, rank=8,
                                                  density=0.05, seed=seed)
        if name == "als":
            vals = np.abs(vals)          # implicit mode consumes counts
        m = np.c_[rows, cols, vals]
        _write_parts(name, np.array_split(m, 2), ["%d", "%d", "%.5f"],
                     f"COO ratings 'row col value' (256 x 256, ~5%); "
                     f"harp_tpu.run {name} --ratings-file datasets/{name}")

    # lda: rectangular token-id corpus (128 docs x 32 tokens, V=200)
    docs = datagen.lda_corpus(128, 200, 8, 32, seed=44)
    _write_parts("lda", np.split(docs, 2), "%d",
                 "token-id corpus, one doc per line, fixed length (128 docs "
                 "x 32 tokens, vocab 200); harp_tpu.run lda --corpus-file "
                 "datasets/lda --vocab 200")

    # svm: labeled dense CSV, label (0/1) in the LAST column
    xs, ys = datagen.classification_data(256, 8, 2, seed=45)
    _write_parts("svm", np.split(np.c_[xs, ys], 2), "%.6f",
                 "labeled dense CSV, label in last column (256 x 8, 2 "
                 "classes); harp_tpu.run svm --train-file datasets/svm",
                 delimiter=",")

    # subgraph: reference-format .template (vertex count + edge list)
    os.makedirs(os.path.join(HERE, "subgraph"), exist_ok=True)
    with open(os.path.join(HERE, "subgraph", "u5-1.template"), "w") as f:
        # 5-vertex path tree (the reference's u5-1 shape): vertex count,
        # edge count, then one edge per line
        f.write("5\n4\n0 1\n1 2\n2 3\n3 4\n")
    with open(os.path.join(HERE, "subgraph", "_README"), "w") as f:
        f.write("reference-format .template (5-vertex path); harp_tpu.run "
                "subgraph --template-file datasets/subgraph/u5-1.template\n")

    print("datasets regenerated under", HERE)


if __name__ == "__main__":
    main()
