"""Gang tour — the distributed runtime end to end on one machine.

Reference parity: the depl/Driver standalone harness (collective/Driver.java:93
launched one JVM per worker; depl/Depl.java:36 read the nodes file) and the
per-algorithm launchers it drove. This tour runs the TPU-native equivalents in
sequence, all on localhost:

  1. gang launch — ``parallel.launch`` starts one process per nodes-file
     entry with the gang env; each member's ``harp_tpu.run kmeans`` joins
     via ``distributed.initialize`` and ONE distributed K-means trains over
     the gang's global mesh, checkpointing every ``--save-every`` epochs
     (master-only writes);
  2. resume — a second identical launch finds the finished checkpoint and
     every member reports a full resume (kill-and-restart without losing
     work — the capability upgrade over the reference's restart-from-zero);
  3. fail-stop — a gang where one member dies is killed promptly instead of
     stalling toward the 1800 s timeout (Communication.java:82 "Slaves may
     fail").

Run: ``python examples/gang_tour.py [workdir]`` (defaults to a temp dir).
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(workdir: str = "", members: int = 2, devices_per_member: int = 2,
         points: int = 512, iterations: int = 4) -> int:
    from harp_tpu.parallel import launch

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    workdir = workdir or tempfile.mkdtemp(prefix="harp-gang-tour-")
    nodes = [launch.Node("localhost", 0) for _ in range(members)]
    train = [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
             "--num-workers", str(devices_per_member),
             "--num-points", str(points), "--num-centroids", "4",
             "--dim", "8", "--iterations", str(iterations),
             "--work-dir", workdir, "--save-every", "2"]

    print(f"[1/3] gang launch: {members} members x {devices_per_member} "
          f"virtual devices, checkpointing into {workdir}")
    results = launch.launch(nodes, train, timeout=600.0, cwd=repo)
    for i, (rc, out) in enumerate(results):
        line = next((ln for ln in out.splitlines() if "kmeans[" in ln), "?")
        print(f"  member {i}: rc={rc} {line.strip()}")
        assert rc == 0, out[-2000:]
    assert os.path.exists(os.path.join(workdir, "centroids.csv"))

    print("[2/3] relaunch: the checkpoint already covers every iteration")
    results = launch.launch(nodes, train, timeout=600.0, cwd=repo)
    for i, (rc, out) in enumerate(results):
        assert rc == 0 and "fully resumed" in out, out[-500:]
        print(f"  member {i}: fully resumed from checkpoint")

    print("[3/3] fail-stop: member 0 exits 3; the gang must die promptly")
    crash = [sys.executable, "-c",
             "import os, sys, time\n"
             "if os.environ['HARP_PROCESS_ID'] == '0':\n"
             "    time.sleep(0.2); sys.exit(3)\n"
             "time.sleep(120)"]
    t0 = time.monotonic()
    results = launch.launch(nodes, crash, timeout=60.0)
    dt = time.monotonic() - t0
    assert results[0][0] == 3 and results[1][0] != 0
    print(f"  gang killed in {dt:.1f}s (survivor rc={results[1][0]})")
    print("gang tour OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else ""))
