"""Analytics-suite tour — the ml/daal families end to end on one mesh.

Reference parity: the role of ml/daal's per-algorithm Launcher mains (each
daal_* family shipped a runnable example job). One script walks the r4
surface: dense + CSR analytics, PCA both methods, kernel/multiclass SVM,
WDA-MDS with non-uniform weights, distributed sort/quantiles, and the
fsspec IO seam. Run with:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/analytics_tour.py
"""

import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax                                             # noqa: E402

# NOT a no-op on TPU images whose sitecustomize force-selects the hardware
# backend via jax.config.update (which OVERRIDES the env var) — calling
# update back is the only way to honor JAX_PLATFORMS=cpu there (the same
# guard every example/test harness in this repo uses; see tests/conftest.py)
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np                                     # noqa: E402

from harp_tpu.io import datagen, loaders               # noqa: E402
from harp_tpu.models import mds, sparse, stats, svm    # noqa: E402
from harp_tpu.session import HarpSession               # noqa: E402


def main():
    sess = HarpSession(num_workers=8)
    rng = np.random.default_rng(0)

    # --- dense analytics: covariance → PCA by both reference methods ----- #
    x = rng.standard_normal((512, 16)).astype(np.float32)
    cov, mean = stats.Covariance(sess).compute(x)
    assert np.allclose(cov, np.cov(x, rowvar=False), atol=1e-4)
    assert np.allclose(mean, x.mean(0), atol=1e-5)
    w_cor, _, _ = stats.PCA(sess, method="cor").fit(x)
    w_svd, _, _ = stats.PCA(sess, method="svd").fit(x)
    assert np.allclose(w_cor, w_svd, atol=1e-3)
    print(f"pca: top eigenvalue {w_cor[0]:.3f} (cor == svd method)")

    # --- CSR analytics: the same answers from sparse input --------------- #
    rows, cols, vals = datagen.sparse_points(512, 16, density=0.2, seed=1)
    cov_csr, _ = sparse.CSRCovariance(sess).compute(rows, cols, vals, 512, 16)
    dense = np.zeros((512, 16), np.float32)
    dense[rows, cols] = vals
    assert np.allclose(cov_csr, np.cov(dense, rowvar=False), atol=1e-4)
    cen, costs = sparse.SparseKMeans(
        sess, sparse.SparseKMeansConfig(4, 16, 5)).fit(
        rows, cols, vals, 512, dense[:4].copy())
    print(f"csr kmeans: cost {costs[0]:.1f} -> {costs[-1]:.1f}")

    # --- kernel SVM: rbf separates what linear cannot -------------------- #
    theta = rng.uniform(0, 2 * np.pi, 256)
    radius = np.where(np.arange(256) % 2 == 0, 1.0, 3.0)
    y = (np.arange(256) % 2 == 0).astype(np.int32)
    pts = (radius[:, None] * np.c_[np.cos(theta), np.sin(theta)]
           + 0.1 * rng.standard_normal((256, 2))).astype(np.float32)
    machine = svm.KernelSVM(sess, svm.KernelSVMConfig(
        kernel="rbf", c=10.0, iterations=250))
    machine.fit(pts, y)
    acc = (machine.predict(pts) == y).mean()
    print(f"kernel svm (rbf, circles): train acc {acc:.3f}, "
          f"{len(machine.sv_x)} support vectors")
    assert acc > 0.95

    # --- WDA-MDS: weighted CG Guttman solve ------------------------------ #
    p2 = rng.standard_normal((64, 2)).astype(np.float32)
    dist = np.sqrt(((p2[:, None] - p2[None]) ** 2).sum(-1)).astype(np.float32)
    wts = rng.uniform(0.5, 2.0, dist.shape).astype(np.float32)
    emb, stress = mds.WDAMDS(sess, mds.MDSConfig(
        dim=2, iterations=30, cg_iters=10)).fit(dist, weights=(wts + wts.T) / 2)
    print(f"wda-mds: stress {stress[0]:.1f} -> {stress[-1]:.1f}")
    assert stress[-1] < stress[0]

    # --- distributed order statistics ------------------------------------ #
    q = stats.Quantiles(sess).compute(x, [0.25, 0.5, 0.75])
    assert np.allclose(q, np.quantile(x, [0.25, 0.5, 0.75], axis=0),
                       atol=1e-4)
    print(f"quantiles (distributed sort): median[0] {q[1, 0]:.3f}")

    # --- fsspec seam: part-files in an object store ---------------------- #
    import fsspec

    with fsspec.open("memory://tour/part-0.csv", "w") as f:
        for row in x[:8]:
            f.write(",".join(f"{v:.6f}" for v in row) + "\n")
    loaded = loaders.load_dense_csv(loaders.list_files("memory://tour/"))
    assert loaded.shape == (8, 16)
    fsspec.filesystem("memory").rm("/tour", recursive=True)
    print("fsspec seam: memory:// part-file round trip OK")
    print("ANALYTICS TOUR OK")


if __name__ == "__main__":
    main()
