"""K-means launcher — the reference CLI, TPU-native.

Reference parity: ``hadoop jar harp-java-0.1.0.jar
edu.iu.kmeans.regroupallgather.KMeansLauncher <numOfDataPoints> <num of
Centroids> <size of vector> <number of map tasks> <number of iteration>
<workDir> <local points file>`` (README.md:148-160). Here the same positional
semantics, minus the Hadoop plumbing:

    python examples/kmeans_launcher.py 1000 10 100 2 10 /tmp/km-work
    python examples/kmeans_launcher.py --comm rotation 100000 100 100 8 10 /tmp/km
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("num_points", type=int)
    p.add_argument("num_centroids", type=int)
    p.add_argument("dim", type=int)
    p.add_argument("num_workers", type=int,
                   help="mesh size (reference: number of map tasks)")
    p.add_argument("iterations", type=int)
    p.add_argument("work_dir")
    p.add_argument("points_file", nargs="?", default=None,
                   help="optional CSV of points; generated if omitted")
    p.add_argument("--comm", default="regroupallgather",
                   help="comm pattern (see models.kmeans.COMM_VARIANTS)")
    p.add_argument("--cpu-mesh", action="store_true",
                   help="force a virtual CPU mesh of num_workers devices")
    args = p.parse_args()

    if args.cpu_mesh:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count="
                                   f"{args.num_workers}")
    import jax

    if args.cpu_mesh:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from harp_tpu.io import datagen, loaders
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession
    from harp_tpu.utils import checkpoint, metrics

    sess = HarpSession(num_workers=min(args.num_workers,
                                       len(jax.devices())))
    if args.points_file:
        pts = loaders.load_dense_csv([args.points_file])
    else:
        pts = datagen.dense_points(args.num_points, args.dim, seed=0,
                                   num_clusters=args.num_centroids)
    n_eff = pts.shape[0] - pts.shape[0] % sess.num_workers
    pts = pts[:n_eff]
    cen0 = datagen.initial_centroids(pts, args.num_centroids, seed=1)

    m = metrics.Metrics()
    model = km.KMeans(sess, km.KMeansConfig(
        args.num_centroids, args.dim, args.iterations, args.comm))
    with m.timer("fit"):
        cen, costs = model.fit(pts, cen0)
        costs = np.asarray(costs)

    os.makedirs(args.work_dir, exist_ok=True)
    # reference: KMUtil.storeCentroids writes the final model to the work dir
    np.savetxt(os.path.join(args.work_dir, "centroids.csv"),
               np.asarray(cen), delimiter=",")
    checkpoint.Checkpointer(os.path.join(args.work_dir, "ckpt")).save(
        args.iterations, {"centroids": np.asarray(cen)})

    t = m.timing("fit")
    print(f"workers={sess.num_workers} comm={args.comm} "
          f"iters={args.iterations} time={t['total_s']:.3f}s "
          f"({args.iterations / t['total_s']:.1f} iters/s incl. compile)")
    print(f"cost: {costs[0]:.1f} -> {costs[-1]:.1f}")
    print(f"model written to {args.work_dir}/centroids.csv")


if __name__ == "__main__":
    main()
