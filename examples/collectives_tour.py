"""API examples — one tiny program per collective.

Reference parity: ml/java examples/ (ExamplesMain.java, AllReduce.java,
Rotate.java, ... — one minimal mapper per collective op). Run with:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/collectives_tour.py
"""

import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax                                             # noqa: E402

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                # noqa: E402
import numpy as np                                     # noqa: E402

from harp_tpu import MAX, HarpSession, Table           # noqa: E402
from harp_tpu.collectives import lax_ops, table_ops    # noqa: E402


def main():
    sess = HarpSession()
    w = sess.num_workers
    print(f"mesh: {w} workers on {jax.default_backend()}")

    # Each example mirrors one reference examples/ mapper: build a LOCAL table
    # of per-worker contributions, run ONE collective, print the result.
    contrib = np.arange(w * 4, dtype=np.float32).reshape(w, 4)

    def allreduce_ex(x):
        t = Table.local(x, num_workers=w)
        return table_ops.allreduce(t).trim()

    def regroup_allgather_ex(x):
        t = Table.local(x, num_workers=w)
        g = table_ops.regroup(t)                    # each worker owns a block
        return table_ops.allgather(g).trim()        # …and shares it back

    def rotate_ex(x):
        t = Table.sharded(x, num_workers=w)
        return table_ops.rotate(t, steps=1).data

    def broadcast_ex(x):
        t = Table.local(x, num_workers=w)
        return table_ops.broadcast(t, root=0).trim()

    def reduce_max_ex(x):
        t = Table.local(x, num_workers=w, combiner=MAX)
        return table_ops.allreduce(t).trim()

    def push_pull_ex(x):
        local = Table.local(x, num_workers=w)
        zero = Table.sharded(jnp.zeros((x.shape[0] // w,) + x.shape[1:]),
                             num_workers=w)
        g = table_ops.push(local, zero)
        return table_ops.pull(g).trim()

    rep = sess.replicate()
    for name, fn, spec in [
        ("allreduce", allreduce_ex, rep),
        ("regroup+allgather", regroup_allgather_ex, rep),
        ("broadcast", broadcast_ex, rep),
        ("allreduce(MAX)", reduce_max_ex, rep),
        ("push/pull", push_pull_ex, rep),
    ]:
        out = sess.run(fn, sess.replicate_put(jnp.asarray(contrib)),
                       in_specs=(rep,), out_specs=spec)
        print(f"{name:>18}: row0 = {np.asarray(out)[0]}")

    # rotate works on the sharded view: worker i's block moves to worker i+1
    blocks = np.arange(w * 2, dtype=np.float32).reshape(w * 2, 1)
    out = sess.run(rotate_ex, sess.scatter(jnp.asarray(blocks)),
                   in_specs=(sess.shard(),), out_specs=sess.shard())
    print(f"{'rotate':>18}: {np.asarray(out).ravel()}")

    # barrier + worker identity (Workers.getSelfID equivalent)
    ids = sess.run(lambda x: x * 0 + lax_ops.worker_id(),
                   sess.scatter(jnp.zeros((w, 1))),
                   in_specs=(sess.shard(),), out_specs=sess.shard())
    print(f"{'worker ids':>18}: {np.asarray(ids).ravel()}")

    # owner-partitioned KV shuffle (GroupByKeyCollective, scalable form)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 16, size=(w, 8)).astype(np.int32)
    vals = np.ones((w, 8), np.float32)

    def group_ex(k, v):
        out, ovf = table_ops.group_by_key_sharded(k[0], v[0], num_keys=16,
                                                  capacity=16)
        return out, ovf

    out, ovf = sess.run(group_ex, keys, vals,
                        in_specs=(sess.shard(), sess.shard()),
                        out_specs=(rep, rep))
    print(f"{'group_by_key':>18}: counts per key = "
          f"{np.asarray(out).astype(int)} (overflow {int(ovf)})")

    # typed KV table (keyval/): routed insert-or-combine + lookup
    from harp_tpu import keyval as kv

    def kv_ex(k, v):
        t = kv.DistributedKV(kv.kv_empty(64, val_dtype=jnp.float32))
        t, _, _ = t.update(k[0], v[0])
        got, found = t.lookup(jnp.arange(8, dtype=jnp.int32))
        return got[None], found[None]

    got, found = sess.run(kv_ex, keys, vals,
                          in_specs=(sess.shard(), sess.shard()),
                          out_specs=(sess.shard(), sess.shard()))
    print(f"{'DistributedKV':>18}: keys 0-7 on worker 0 = "
          f"{np.asarray(got)[0].astype(int)}, found = "
          f"{np.asarray(found)[0].astype(int)}")


if __name__ == "__main__":
    main()
